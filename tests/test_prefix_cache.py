"""Prefix-cache block sharing (the radix-tree index in
runtime/prefix_cache.py + the ref-counted BlockAllocator in
runtime/serving.py + the admission policies in runtime/scheduling.py).

Fast tier: the index, allocator, and policies are pure host code, and
the engine scheduling tests run the cyclic stub model, so the sharing
invariants — no block freed or evicted while referenced, leaf-first
eviction (an interior run outlives its cached tails), CoW instead of
in-place mutation, deferral instead of duplicate prefill, multi-turn
completion chains — are checked on every dev-lane run. The llama-backed
exactness tiers (prefix-on == prefix-off == isolated decode, across
fp / int8 / speculative, fifo vs cache-aware) live in
tests/test_serving.py with the rest of the compile-bound contract."""

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nexus_tpu.runtime.prefix_cache import PrefixCacheIndex, chain_keys
from nexus_tpu.runtime.scheduling import (
    CacheAwareAdmission,
    FifoAdmission,
    make_admission_policy,
)
from nexus_tpu.runtime.serving import (
    BlockAllocator,
    ServeRequest,
    ServingEngine,
)


def _cyclic_model(v: int):
    """next = (token + 1) % v — deterministic, no K/V reads (scheduling
    and allocation are under test; the real paged-attention read path is
    covered by test_serving.py's llama tiers)."""
    cfg = SimpleNamespace(
        n_layers=1, n_kv_heads=1, head_dim=8, dtype=jnp.float32,
        max_seq_len=256, vocab_size=v,
    )

    def fwd(params, cfg_, tokens, cache):
        logits = jax.nn.one_hot((tokens + 1) % v, v) * 10.0
        new = {k: x for k, x in cache.items() if k != "n_valid"}
        nv = cache.get("n_valid")
        adv = tokens.shape[1] if nv is None else nv
        new["length"] = cache["length"] + adv
        return logits.astype(jnp.float32), new

    return cfg, fwd


def _expect(req, v):
    out = []
    cur = req.prompt[-1]
    for _ in range(req.max_new_tokens):
        cur = (cur + 1) % v
        out.append(cur)
    return list(req.prompt) + out


# ---------------------------------------------------------------- keys


def test_chain_keys_commit_to_the_whole_prefix():
    toks = list(range(20))
    keys = chain_keys(toks, 4)
    assert len(keys) == 5  # only FULL blocks are keyed
    assert chain_keys(toks[:19], 4) == keys[:4]  # partial tail dropped
    # same prefix -> same leading keys; a divergence poisons every
    # later key (each digest chains over all earlier blocks)
    other = list(toks)
    other[5] = 99
    ok = chain_keys(other, 4)
    assert ok[0] == keys[0]
    assert all(a != b for a, b in zip(ok[1:], keys[1:]))
    assert chain_keys(toks, 4, limit=2) == keys[:2]
    with pytest.raises(ValueError):
        chain_keys(toks, 0)


def test_index_match_park_evict_roundtrip():
    idx = PrefixCacheIndex()
    keys = chain_keys(list(range(12)), 4)
    assert idx.match(keys) == []
    assert idx.insert(keys[0], 7)
    assert idx.insert(keys[1], 3, parent=keys[0])
    assert idx.insert(keys[0], 9) is False  # first writer wins
    assert idx.insert(keys[2], 7, parent=keys[1]) is False  # one id/block
    assert idx.match(keys) == [7, 3]
    # an orphan insert (ancestor never indexed / already evicted) is
    # REFUSED — the flat index kept unmatchable orphans, the tree won't
    assert idx.insert(keys[2], 5, parent=b"missing") is False
    assert idx.insert(keys[2], 5, parent=keys[1])
    idx.audit()
    # a miss mid-chain stops the walk
    assert idx.match([keys[0], b"missing", keys[2]]) == [7]
    # park in release order (ancestors may park first within a release)
    idx.park(7)
    idx.park(3)
    idx.park(5)
    idx.unpark(5)  # revived by a shared admission
    assert idx.parked_count == 2
    # LEAF-FIRST: 7 and 3 are both parked and LRU-older than nothing
    # evictable — but each still has an indexed descendant, and 5 (the
    # only leaf) is referenced, so eviction must refuse rather than
    # strand the chain
    with pytest.raises(RuntimeError):
        idx.evict_lru()
    idx.park(5)
    # now the LRU scan skips the parked ancestors and takes the leaf
    assert idx.evict_lru() == 5
    assert idx.match(keys) == [7, 3]  # interior run intact
    assert idx.evict_lru() == 3  # new leaf tail
    assert idx.match(keys) == [7]
    with pytest.raises(ValueError):
        idx.park(99)  # never indexed
    assert idx.evict_lru() == 7
    with pytest.raises(RuntimeError):
        idx.evict_lru()  # nothing parked
    idx.audit()


def test_radix_branching_chains_share_preamble_subtree():
    """Two few-shot variants of one system prompt: the shared preamble
    is ONE interior run, the tails are sibling leaves, and match()
    returns the longest cached prefix for either branch — the structure
    the flat single-chain matcher could only represent digest by
    digest, with no eviction ordering between ancestor and tail."""
    bs = 4
    pre = list(range(8))  # 2 preamble blocks
    a = pre + [101, 102, 103, 104] * 2  # 2 private tail blocks
    b = pre + [201, 202, 203, 204]  # 1 private tail block
    ka, kb = chain_keys(a, bs), chain_keys(b, bs)
    assert ka[:2] == kb[:2]  # digest chaining: shared preamble
    idx = PrefixCacheIndex()
    for j, (k, blk) in enumerate(zip(ka, [0, 1, 2, 3])):
        assert idx.insert(k, blk, parent=ka[j - 1] if j else None)
    # branch B attaches at the divergence point — mid-run split
    assert idx.insert(kb[2], 4, parent=kb[1])
    idx.audit()
    assert idx.match(ka) == [0, 1, 2, 3]
    assert idx.match(kb) == [0, 1, 4]
    # a third branch diverging INSIDE the preamble splits again
    c = pre[:4] + [7, 7, 7, 7]
    kc = chain_keys(c, bs)
    assert idx.insert(kc[1], 5, parent=kc[0])
    idx.audit()
    assert idx.match(kc) == [0, 5]
    assert idx.match(ka) == [0, 1, 2, 3]  # older chains unharmed
    # leaf-first eviction under the branched tree: park everything in
    # ancestor-first order; eviction must take tails before the shared
    # preamble blocks whatever the LRU order says
    for blk in (0, 1, 2, 3, 4, 5):
        idx.park(blk)
    evicted = [idx.evict_lru() for _ in range(6)]
    for pos, blk in enumerate(evicted):
        # when a block is evicted, no earlier-evicted... every block
        # must come out strictly after all its descendants
        assert blk in (0, 1, 2, 3, 4, 5)
    # block 0 (the preamble root) must be the LAST standing ancestor
    assert evicted[-1] == 0
    # and block 1 (interior with three dependants at peak) comes out
    # only after 2, 3, and 4
    assert evicted.index(1) > max(
        evicted.index(2), evicted.index(3), evicted.index(4)
    )


# ----------------------------------------------------- allocator refs


def test_allocator_shared_admission_refcounts():
    idx = PrefixCacheIndex()
    a = BlockAllocator(num_blocks=8, block_size=4, prefix_index=idx)
    leader = a.admit(4)
    blks = leader.grow_to(4)
    keys = chain_keys(list(range(16)), 4)
    for j, (k, blk) in enumerate(zip(keys, blks[:2])):
        a.register_block(k, blk, parent=keys[j - 1] if j else None)
    # follower maps the two indexed blocks shared + 2 private
    shared, skeys, matched, cow = a.match_prefix(keys, prompt_len=16)
    assert shared == blks[:2] and matched == 8 and cow is None
    assert skeys == []  # nothing spilled without a host tier
    follower = a.admit(2, shared=shared)
    assert follower is not None
    assert follower.blocks[:2] == blks[:2]
    # leader releases: the shared blocks stay ALIVE (follower's refs),
    # the unindexed privates go back to the free list
    leader.release()
    assert a.cached_blocks == 0  # still referenced -> not parked
    assert a.free_blocks == 6  # 2 of the leader's 4 were shared
    follower.grow_to(4)
    follower.release()
    # last reference parks the indexed content instead of freeing it
    assert a.cached_blocks == 2
    assert a.free_blocks == 6
    assert a.available_blocks == 8  # parked blocks stay admissible
    # and the content is still matchable
    assert a.match_prefix(keys, 16)[0] == blks[:2]


def test_allocator_full_prompt_hit_returns_cow_source():
    idx = PrefixCacheIndex()
    a = BlockAllocator(num_blocks=8, block_size=4, prefix_index=idx)
    lease = a.admit(3)
    blks = lease.grow_to(3)
    keys = chain_keys(list(range(12)), 4)
    for j, (k, blk) in enumerate(zip(keys, blks)):
        a.register_block(k, blk, parent=keys[j - 1] if j else None)
    # block-aligned full-prompt hit: the cap at p-1 lands INSIDE the
    # last matched block -> shared stops before it, cow_src returns it
    shared, skeys, matched, cow = a.match_prefix(keys, prompt_len=12)
    assert shared == blks[:2] and matched == 11 and cow == blks[2]
    assert skeys == []


def test_allocator_evicts_lru_refcount0_only_under_pressure():
    idx = PrefixCacheIndex()
    a = BlockAllocator(num_blocks=4, block_size=4, prefix_index=idx)
    l1 = a.admit(4)
    blks = l1.grow_to(4)
    keys = chain_keys(list(range(16)), 4)
    for j, (k, blk) in enumerate(zip(keys, blks[:2])):
        a.register_block(k, blk, parent=keys[j - 1] if j else None)
    l1.release()  # 2 parked (cached), 2 free
    assert a.cached_blocks == 2 and a.free_blocks == 2
    assert a.evictions == 0
    # a new 4-block admission drains the free list then reclaims the
    # parked pair LRU-first — eviction only under pressure, and only of
    # refcount-0 blocks (the free list is consumed first)
    l2 = a.admit(4)
    assert l2 is not None
    got = l2.grow_to(4)
    assert a.evictions == 2
    assert sorted(got) == [0, 1, 2, 3]
    assert a.match_prefix(keys, 16) == ([], [], 0, None)  # content gone
    # while REFERENCED the same blocks are never evictable
    assert a.admit(1) is None


# ------------------------------------------------ admission policies


def test_admission_policies_order_and_aging():
    fifo = FifoAdmission()
    assert fifo.order([3, 1, 2], {}, lambda i: 100) == [3, 1, 2]
    ca = CacheAwareAdmission(aging_waves=2)
    match = {1: 0, 2: 32, 3: 16}
    assert ca.order([1, 2, 3], {}, lambda i: match[i]) == [2, 3, 1]
    # ties keep arrival order (stable sort): a cold cache degrades the
    # cache-aware policy to exact FIFO
    assert ca.order([4, 5, 6], {}, lambda i: 0) == [4, 5, 6]
    # aged requests outrank every fresher arrival, FIFO among themselves
    waits = {1: 2, 3: 5}
    assert ca.order([1, 2, 3], waits, lambda i: match[i]) == [1, 3, 2]
    with pytest.raises(ValueError):
        CacheAwareAdmission(aging_waves=0)
    with pytest.raises(ValueError):
        make_admission_policy("lifo")
    assert isinstance(make_admission_policy("fifo"), FifoAdmission)
    custom = CacheAwareAdmission(aging_waves=3)
    assert make_admission_policy(custom) is custom  # pluggable instance


# -------------------------------------------------- engine scheduling


def test_engine_shared_prefix_skips_prefill_and_stays_exact():
    """6 requests sharing a 17-token preamble through 2 rows: every
    output exact, and the cache saves most of the repeated prefill
    (leader computes the preamble once; deferral keeps followers from
    duplicating it, then they admit together with hits)."""
    v = 11
    cfg, fwd = _cyclic_model(v)
    rng = np.random.RandomState(7)
    common = rng.randint(0, v, size=17).tolist()
    reqs = [
        ServeRequest(
            prompt=common + rng.randint(0, v, size=p).tolist(),
            max_new_tokens=n,
        )
        for p, n in ((9, 6), (13, 5), (4, 8), (9, 4), (6, 7), (11, 3))
    ]
    metrics = {}
    outs = {}
    for pc in (False, True):
        eng = ServingEngine(
            fwd, {}, cfg, batch_size=2, max_len=96, chunk=4,
            kv_block_size=8, prefix_cache=pc,
        )
        results, m = eng.serve(reqs)
        for i, (req, res) in enumerate(zip(reqs, results)):
            assert res.tokens == _expect(req, v), (pc, i)
        metrics[pc], outs[pc] = m, [r.tokens for r in results]
    assert outs[False] == outs[True]
    on, off = metrics[True], metrics[False]
    assert on["prefix_cache"] is True and off["prefix_cache"] is False
    assert on["prefix_hit_tokens"] > 0
    assert on["prefix_hit_requests"] >= 5  # every follower hits
    assert on["prefill_steps"] < off["prefill_steps"]
    assert on["prefix_prefill_steps_saved"] == (
        off["prefill_steps"] - on["prefill_steps"]
    )
    # sharing shrinks what a request RESERVES, so the per-request KV
    # ledger must undercut the cache-off engine's
    assert on["kv_bytes_per_request"] < off["kv_bytes_per_request"]


def test_engine_full_duplicate_prompt_takes_cow_path():
    """A block-aligned exact-duplicate prompt matches its ENTIRE chain:
    the engine recomputes only the last position into a copy-on-write
    private block — one CoW copy, output still exact, and the frozen
    original keeps serving later duplicates."""
    v = 9
    cfg, fwd = _cyclic_model(v)
    base = [1, 2, 3, 4, 5, 6, 7, 8] * 2  # 16 tokens = 2 blocks of 8
    reqs = [
        ServeRequest(prompt=list(base), max_new_tokens=4)
        for _ in range(3)
    ]
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
        kv_block_size=8, prefix_cache=True,
    )
    results, m = eng.serve(reqs)
    for res in results:
        assert res.tokens == _expect(reqs[0], v)
    assert m["prefix_cow_copies"] == 2  # both duplicates CoW the tail
    assert m["prefix_hit_tokens"] == 2 * (len(base) - 1)
    # duplicates prefill exactly ONE position each (the capped last)
    assert m["prefill_steps"] == -(-16 // 8) + 2


def test_engine_eviction_under_tight_pool_stays_exact():
    """Alternating prefix groups through a pool too small to cache both:
    evictions happen (refcount-0 blocks only, by construction), the
    queue drains completely and exactly."""
    v = 13
    cfg, fwd = _cyclic_model(v)
    rng = np.random.RandomState(5)
    g1 = rng.randint(0, v, size=16).tolist()
    g2 = rng.randint(0, v, size=16).tolist()
    reqs = []
    for g in (g1, g2, g1, g2):
        reqs.append(ServeRequest(
            prompt=g + rng.randint(0, v, size=4).tolist(),
            max_new_tokens=4,
        ))
    # per request: cap = 20 + 4 + slack(4) + 1 = 29 -> 4 blocks of 8;
    # a 4-block pool can't keep a group cached past the next group
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
        kv_block_size=8, kv_num_blocks=4, prefix_cache=True,
    )
    results, m = eng.serve(reqs)
    for req, res in zip(reqs, results):
        assert res.tokens == _expect(req, v)
    assert m["prefix_evictions"] > 0
    assert m["kv_peak_allocated_blocks"] <= 4


def test_engine_multiturn_completion_chain_hits():
    """A successor whose prompt is a prior request's full prompt +
    completion (multi-turn chat) matches the prior turn's WHOLE chain:
    decoded blocks are registered into the radix tree at release. The
    round-6 prompt-only matcher (prefix_completions=False) hits only
    the old prompt half — the A/B the bench scenarios measure."""
    v = 17
    cfg, fwd = _cyclic_model(v)
    rng = np.random.RandomState(11)
    p1 = rng.randint(0, v, size=16).tolist()
    turn1 = ServeRequest(prompt=p1, max_new_tokens=17)
    full1 = _expect(turn1, v)  # 33 tokens: what turn 1 will commit
    turn2 = ServeRequest(
        prompt=full1 + rng.randint(0, v, size=7).tolist(),
        max_new_tokens=6,
    )
    metrics = {}
    for completions in (True, False):
        eng = ServingEngine(
            fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
            kv_block_size=8, prefix_cache=True,
            prefix_completions=completions,
        )
        results, metrics[completions] = eng.serve([turn1, turn2])
        assert results[0].tokens == full1
        assert results[1].tokens == _expect(turn2, v)
    radix, chain = metrics[True], metrics[False]
    # frozen turn-1 tokens = 16 + 17 - 1 = 32 -> blocks 2..3 hold
    # decoded content and enter the tree at release
    assert radix["prefix_completion_blocks"] == 2
    assert chain["prefix_completion_blocks"] == 0
    # turn 2 matches the prior turn's full 4-block chain vs only the
    # 2 prompt blocks — the multi-turn surface the ROADMAP names
    assert radix["prefix_hit_tokens"] > chain["prefix_hit_tokens"]
    assert radix["prefix_hit_depth_hist"].get(4) == 1
    assert chain["prefix_hit_depth_hist"].get(2) == 1


def test_engine_cache_aware_admission_prefers_resident_match():
    """One row, three requests: once the leader's chain parks, the
    cache-aware queue admits the request that can reuse it ahead of an
    OLDER cold request (bounded by aging) — and outputs stay identical
    to fifo, because ordering is scheduling, never semantics."""
    v = 13
    cfg, fwd = _cyclic_model(v)
    rng = np.random.RandomState(3)
    warm = rng.randint(0, v, size=16).tolist()
    cold = rng.randint(0, v, size=16).tolist()
    reqs = [
        ServeRequest(prompt=warm, max_new_tokens=4),
        ServeRequest(prompt=cold, max_new_tokens=4),  # arrives second
        ServeRequest(prompt=warm + [1, 2, 3], max_new_tokens=4),  # third
    ]
    out = {}
    for policy in ("fifo", "cache-aware"):
        eng = ServingEngine(
            fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
            kv_block_size=8, prefix_cache=True, admission_policy=policy,
        )
        results, m = eng.serve(reqs)
        for req, r in zip(reqs, results):
            assert r.tokens == _expect(req, v)
        out[policy] = (m, [r.queue_s for r in results])
    m_fifo, q_fifo = out["fifo"]
    m_ca, q_ca = out["cache-aware"]
    assert m_fifo["admission_policy"] == "fifo"
    assert m_ca["admission_policy"] == "cache-aware"
    assert m_fifo["admission_overtakes"] == 0
    # cache-aware admitted the warm follower ahead of the older cold
    # request exactly once (then the cold one went — no starvation)
    assert m_ca["admission_overtakes"] == 1
    assert q_ca[2] <= q_ca[1]  # warm follower admitted first
    assert q_fifo[1] <= q_fifo[2]  # fifo kept arrival order


def test_engine_reports_ttft_and_queue_percentiles():
    v = 7
    cfg, fwd = _cyclic_model(v)
    reqs = [ServeRequest(prompt=[1, 2, 3], max_new_tokens=6)
            for _ in range(6)]
    eng = ServingEngine(fwd, {}, cfg, batch_size=2, max_len=64, chunk=4)
    results, m = eng.serve(reqs)
    for res in results:
        # enqueue -> admission -> first token -> finish is monotone
        assert 0.0 <= res.queue_s <= res.latency_s
        assert 0.0 <= res.ttft_s <= res.latency_s
    assert m["ttft_p50_s"] <= m["ttft_p95_s"]
    assert m["queue_p50_s"] <= m["queue_p95_s"]
    # later admissions waited for rows: the queue percentiles must see
    # nonzero waits on a 6-requests / 2-rows run
    assert max(r.queue_s for r in results) > 0.0


def test_prefix_cache_off_by_dense_layout():
    """prefix_cache=True on the dense layout is inert (no block unit to
    share) — the knob must not leak into dense metrics."""
    v = 7
    cfg, fwd = _cyclic_model(v)
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=64, chunk=4,
        kv_block_size=0, prefix_cache=True,
    )
    results, m = eng.serve(
        [ServeRequest(prompt=[1, 2, 3], max_new_tokens=4)]
    )
    assert results[0].tokens == _expect(
        ServeRequest(prompt=[1, 2, 3], max_new_tokens=4), v
    )
    assert m["kv_layout"] == "dense"
    assert "prefix_hit_tokens" not in m


def test_engine_overlapping_turns_keep_tree_closure():
    """Registration guard regression (round-9 review): a turn-2
    successor admitted WHILE its turn-1 predecessor still decodes
    duplicates the completion region in its own blocks; when the
    predecessor releases first and registers that chain, the
    successor's duplicate registrations are refused first-writer-wins
    — and its private TAIL must then NOT attach under the
    predecessor's now-parked run (a referenced child below a parked
    block breaks descendant closure: the per-wave radix audit fires,
    and under pool pressure leaf-first eviction could find no
    reclaimable leaf). The guard stops the successor's chain at the
    first position held by another lease's block.

    Timing (chunk 4, prefill_chunk 1, batch 2): B prefills 16 + decodes
    12 (releases at the step-28 boundary, registering completion block
    k2); filler C1 frees its row at 24, so A (prompt = B's full
    28-token chain + 7) admits at 24 matching only the 2 published
    PROMPT blocks, and crosses the k2 boundary at 32 — after B already
    holds k2. D, E and F keep admission waves (and the armed per-wave
    audit) running through the window where A's tail would have
    attached under B's parked run."""
    v = 19
    cfg, fwd = _cyclic_model(v)
    rng = np.random.RandomState(5)
    p1 = rng.randint(0, v, size=16).tolist()
    turn1 = ServeRequest(prompt=p1, max_new_tokens=12)
    full1 = _expect(turn1, v)  # 28 tokens -> completion block k2
    turn2 = ServeRequest(
        prompt=full1 + rng.randint(0, v, size=7).tolist(),
        max_new_tokens=6,
    )  # 35-token prompt: k0..k3, k3 unique to A
    c1 = ServeRequest(prompt=[1, 2, 3], max_new_tokens=19)
    d = ServeRequest(prompt=[4, 5, 6], max_new_tokens=6)
    e = ServeRequest(prompt=[7, 8, 9], max_new_tokens=4)
    f = ServeRequest(prompt=[2, 3, 4], max_new_tokens=4)
    reqs = [turn1, c1, turn2, d, e, f]
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=2, max_len=96, chunk=4,
        prefill_chunk=1, kv_block_size=8,
    )
    eng._sanitize = True  # per-wave radix audit armed
    results, m = eng.serve(reqs)
    for req, res in zip(reqs, results):
        assert res.tokens == _expect(req, v)
    # the race actually ran: B registered its completion block, and A
    # admitted seeing only the two published prompt blocks (depth 2)
    assert m["prefix_completion_blocks"] >= 1
    assert m["prefix_hit_depth_hist"].get(2) == 1
    # the guard held: A's tail k3 never entered the tree (its k2
    # predecessor is held by B's block, not A's), so the full 4-block
    # chain matches only 3 deep — pre-guard this matched 4 and the
    # per-wave audit raised on the parked-run/referenced-child state
    idx = eng.last_prefix_index
    assert idx is not None
    assert len(idx.match(chain_keys(turn2.prompt, 8))) == 3
    idx.audit()
