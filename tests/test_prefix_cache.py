"""Prefix-cache block sharing (runtime/prefix_cache.py + the ref-counted
BlockAllocator in runtime/serving.py).

Fast tier: the index and allocator are pure host code, and the engine
scheduling tests run the cyclic stub model, so the sharing invariants —
no block freed or evicted while referenced, CoW instead of in-place
mutation, deferral instead of duplicate prefill — are checked on every
dev-lane run. The llama-backed exactness tiers (prefix-on == prefix-off
== isolated decode, across fp / int8 / speculative) live in
tests/test_serving.py with the rest of the compile-bound contract."""

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nexus_tpu.runtime.prefix_cache import PrefixCacheIndex, chain_keys
from nexus_tpu.runtime.serving import (
    BlockAllocator,
    ServeRequest,
    ServingEngine,
)


def _cyclic_model(v: int):
    """next = (token + 1) % v — deterministic, no K/V reads (scheduling
    and allocation are under test; the real paged-attention read path is
    covered by test_serving.py's llama tiers)."""
    cfg = SimpleNamespace(
        n_layers=1, n_kv_heads=1, head_dim=8, dtype=jnp.float32,
        max_seq_len=256, vocab_size=v,
    )

    def fwd(params, cfg_, tokens, cache):
        logits = jax.nn.one_hot((tokens + 1) % v, v) * 10.0
        new = {k: x for k, x in cache.items() if k != "n_valid"}
        nv = cache.get("n_valid")
        adv = tokens.shape[1] if nv is None else nv
        new["length"] = cache["length"] + adv
        return logits.astype(jnp.float32), new

    return cfg, fwd


def _expect(req, v):
    out = []
    cur = req.prompt[-1]
    for _ in range(req.max_new_tokens):
        cur = (cur + 1) % v
        out.append(cur)
    return list(req.prompt) + out


# ---------------------------------------------------------------- keys


def test_chain_keys_commit_to_the_whole_prefix():
    toks = list(range(20))
    keys = chain_keys(toks, 4)
    assert len(keys) == 5  # only FULL blocks are keyed
    assert chain_keys(toks[:19], 4) == keys[:4]  # partial tail dropped
    # same prefix -> same leading keys; a divergence poisons every
    # later key (each digest chains over all earlier blocks)
    other = list(toks)
    other[5] = 99
    ok = chain_keys(other, 4)
    assert ok[0] == keys[0]
    assert all(a != b for a, b in zip(ok[1:], keys[1:]))
    assert chain_keys(toks, 4, limit=2) == keys[:2]
    with pytest.raises(ValueError):
        chain_keys(toks, 0)


def test_index_match_park_evict_roundtrip():
    idx = PrefixCacheIndex()
    keys = chain_keys(list(range(12)), 4)
    assert idx.match(keys) == []
    assert idx.put(keys[0], 7) and idx.put(keys[1], 3)
    assert idx.put(keys[0], 9) is False  # first writer wins
    assert idx.put(keys[2], 7) is False  # one identity per block
    assert idx.match(keys) == [7, 3]
    # a miss mid-chain stops the walk (orphans never match)
    idx.put(chain_keys(list(range(12)), 4)[2], 5)
    assert idx.match([keys[0], b"missing", keys[2]]) == [7]
    idx.park(7)
    idx.park(3)
    idx.unpark(7)  # revived by a shared admission
    assert idx.parked_count == 1
    assert idx.evict_lru() == 3
    assert idx.match(keys) == [7]  # 3's digest is gone
    with pytest.raises(ValueError):
        idx.park(99)  # never indexed
    idx.park(7)
    idx.evict_lru()
    with pytest.raises(RuntimeError):
        idx.evict_lru()  # nothing parked


# ----------------------------------------------------- allocator refs


def test_allocator_shared_admission_refcounts():
    idx = PrefixCacheIndex()
    a = BlockAllocator(num_blocks=8, block_size=4, prefix_index=idx)
    leader = a.admit(4)
    blks = leader.grow_to(4)
    keys = chain_keys(list(range(16)), 4)
    for k, blk in zip(keys, blks[:2]):
        a.register_block(k, blk)
    # follower maps the two indexed blocks shared + 2 private
    shared, matched, cow = a.match_prefix(keys, prompt_len=16)
    assert shared == blks[:2] and matched == 8 and cow is None
    follower = a.admit(2, shared=shared)
    assert follower is not None
    assert follower.blocks[:2] == blks[:2]
    # leader releases: the shared blocks stay ALIVE (follower's refs),
    # the unindexed privates go back to the free list
    leader.release()
    assert a.cached_blocks == 0  # still referenced -> not parked
    assert a.free_blocks == 6  # 2 of the leader's 4 were shared
    follower.grow_to(4)
    follower.release()
    # last reference parks the indexed content instead of freeing it
    assert a.cached_blocks == 2
    assert a.free_blocks == 6
    assert a.available_blocks == 8  # parked blocks stay admissible
    # and the content is still matchable
    assert a.match_prefix(keys, 16)[0] == blks[:2]


def test_allocator_full_prompt_hit_returns_cow_source():
    idx = PrefixCacheIndex()
    a = BlockAllocator(num_blocks=8, block_size=4, prefix_index=idx)
    lease = a.admit(3)
    blks = lease.grow_to(3)
    keys = chain_keys(list(range(12)), 4)
    for k, blk in zip(keys, blks):
        a.register_block(k, blk)
    # block-aligned full-prompt hit: the cap at p-1 lands INSIDE the
    # last matched block -> shared stops before it, cow_src returns it
    shared, matched, cow = a.match_prefix(keys, prompt_len=12)
    assert shared == blks[:2] and matched == 11 and cow == blks[2]


def test_allocator_evicts_lru_refcount0_only_under_pressure():
    idx = PrefixCacheIndex()
    a = BlockAllocator(num_blocks=4, block_size=4, prefix_index=idx)
    l1 = a.admit(4)
    blks = l1.grow_to(4)
    keys = chain_keys(list(range(16)), 4)
    for k, blk in zip(keys, blks[:2]):
        a.register_block(k, blk)
    l1.release()  # 2 parked (cached), 2 free
    assert a.cached_blocks == 2 and a.free_blocks == 2
    assert a.evictions == 0
    # a new 4-block admission drains the free list then reclaims the
    # parked pair LRU-first — eviction only under pressure, and only of
    # refcount-0 blocks (the free list is consumed first)
    l2 = a.admit(4)
    assert l2 is not None
    got = l2.grow_to(4)
    assert a.evictions == 2
    assert sorted(got) == [0, 1, 2, 3]
    assert a.match_prefix(keys, 16) == ([], 0, None)  # content gone
    # while REFERENCED the same blocks are never evictable
    assert a.admit(1) is None


# -------------------------------------------------- engine scheduling


def test_engine_shared_prefix_skips_prefill_and_stays_exact():
    """6 requests sharing a 17-token preamble through 2 rows: every
    output exact, and the cache saves most of the repeated prefill
    (leader computes the preamble once; deferral keeps followers from
    duplicating it, then they admit together with hits)."""
    v = 11
    cfg, fwd = _cyclic_model(v)
    rng = np.random.RandomState(7)
    common = rng.randint(0, v, size=17).tolist()
    reqs = [
        ServeRequest(
            prompt=common + rng.randint(0, v, size=p).tolist(),
            max_new_tokens=n,
        )
        for p, n in ((9, 6), (13, 5), (4, 8), (9, 4), (6, 7), (11, 3))
    ]
    metrics = {}
    outs = {}
    for pc in (False, True):
        eng = ServingEngine(
            fwd, {}, cfg, batch_size=2, max_len=96, chunk=4,
            kv_block_size=8, prefix_cache=pc,
        )
        results, m = eng.serve(reqs)
        for i, (req, res) in enumerate(zip(reqs, results)):
            assert res.tokens == _expect(req, v), (pc, i)
        metrics[pc], outs[pc] = m, [r.tokens for r in results]
    assert outs[False] == outs[True]
    on, off = metrics[True], metrics[False]
    assert on["prefix_cache"] is True and off["prefix_cache"] is False
    assert on["prefix_hit_tokens"] > 0
    assert on["prefix_hit_requests"] >= 5  # every follower hits
    assert on["prefill_steps"] < off["prefill_steps"]
    assert on["prefix_prefill_steps_saved"] == (
        off["prefill_steps"] - on["prefill_steps"]
    )
    # sharing shrinks what a request RESERVES, so the per-request KV
    # ledger must undercut the cache-off engine's
    assert on["kv_bytes_per_request"] < off["kv_bytes_per_request"]


def test_engine_full_duplicate_prompt_takes_cow_path():
    """A block-aligned exact-duplicate prompt matches its ENTIRE chain:
    the engine recomputes only the last position into a copy-on-write
    private block — one CoW copy, output still exact, and the frozen
    original keeps serving later duplicates."""
    v = 9
    cfg, fwd = _cyclic_model(v)
    base = [1, 2, 3, 4, 5, 6, 7, 8] * 2  # 16 tokens = 2 blocks of 8
    reqs = [
        ServeRequest(prompt=list(base), max_new_tokens=4)
        for _ in range(3)
    ]
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
        kv_block_size=8, prefix_cache=True,
    )
    results, m = eng.serve(reqs)
    for res in results:
        assert res.tokens == _expect(reqs[0], v)
    assert m["prefix_cow_copies"] == 2  # both duplicates CoW the tail
    assert m["prefix_hit_tokens"] == 2 * (len(base) - 1)
    # duplicates prefill exactly ONE position each (the capped last)
    assert m["prefill_steps"] == -(-16 // 8) + 2


def test_engine_eviction_under_tight_pool_stays_exact():
    """Alternating prefix groups through a pool too small to cache both:
    evictions happen (refcount-0 blocks only, by construction), the
    queue drains completely and exactly."""
    v = 13
    cfg, fwd = _cyclic_model(v)
    rng = np.random.RandomState(5)
    g1 = rng.randint(0, v, size=16).tolist()
    g2 = rng.randint(0, v, size=16).tolist()
    reqs = []
    for g in (g1, g2, g1, g2):
        reqs.append(ServeRequest(
            prompt=g + rng.randint(0, v, size=4).tolist(),
            max_new_tokens=4,
        ))
    # per request: cap = 20 + 4 + slack(4) + 1 = 29 -> 4 blocks of 8;
    # a 4-block pool can't keep a group cached past the next group
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
        kv_block_size=8, kv_num_blocks=4, prefix_cache=True,
    )
    results, m = eng.serve(reqs)
    for req, res in zip(reqs, results):
        assert res.tokens == _expect(req, v)
    assert m["prefix_evictions"] > 0
    assert m["kv_peak_allocated_blocks"] <= 4


def test_engine_reports_ttft_and_queue_percentiles():
    v = 7
    cfg, fwd = _cyclic_model(v)
    reqs = [ServeRequest(prompt=[1, 2, 3], max_new_tokens=6)
            for _ in range(6)]
    eng = ServingEngine(fwd, {}, cfg, batch_size=2, max_len=64, chunk=4)
    results, m = eng.serve(reqs)
    for res in results:
        # enqueue -> admission -> first token -> finish is monotone
        assert 0.0 <= res.queue_s <= res.latency_s
        assert 0.0 <= res.ttft_s <= res.latency_s
    assert m["ttft_p50_s"] <= m["ttft_p95_s"]
    assert m["queue_p50_s"] <= m["queue_p95_s"]
    # later admissions waited for rows: the queue percentiles must see
    # nonzero waits on a 6-requests / 2-rows run
    assert max(r.queue_s for r in results) > 0.0


def test_prefix_cache_off_by_dense_layout():
    """prefix_cache=True on the dense layout is inert (no block unit to
    share) — the knob must not leak into dense metrics."""
    v = 7
    cfg, fwd = _cyclic_model(v)
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=64, chunk=4,
        kv_block_size=0, prefix_cache=True,
    )
    results, m = eng.serve(
        [ServeRequest(prompt=[1, 2, 3], max_new_tokens=4)]
    )
    assert results[0].tokens == _expect(
        ServeRequest(prompt=[1, 2, 3], max_new_tokens=4), v
    )
    assert m["kv_layout"] == "dense"
    assert "prefix_hit_tokens" not in m
