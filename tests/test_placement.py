"""Topology-aware placement (BASELINE config #5).

A template's workgroup_ref resolves to a workgroup whose cluster /
capabilities select which shard clusters (TPU slice pools) receive the
template. No resolvable workgroup → all shards (reference parity with
controller.go:790's unconditional fan-out).
"""

import pytest

from nexus_tpu.api.template import (
    Container,
    NexusAlgorithmSpec,
    NexusAlgorithmTemplate,
    WorkgroupRef,
)
from nexus_tpu.api.types import ObjectMeta
from nexus_tpu.api.workgroup import (
    NexusAlgorithmWorkgroup,
    NexusAlgorithmWorkgroupSpec,
)
from nexus_tpu.cluster.store import ClusterStore
from nexus_tpu.controller.controller import Controller, SyncError
from nexus_tpu.controller.events import REASON_ERR_PLACEMENT, FakeRecorder
from nexus_tpu.controller.placement import PlacementError, select_shards
from nexus_tpu.shards.shard import Shard
from nexus_tpu.utils.telemetry import StatsdClient

NS = "nexus"
ALIAS = "test-controller-cluster"

SHARD_CAPS = {
    "pool-v5e": {"tpu-v5e": True},
    "pool-v5p-a": {"tpu-v5p": True, "moe": True},
    "pool-v5p-b": {"tpu-v5p": True, "moe": True},
}


def make_template(name="algo-1", workgroup=""):
    return NexusAlgorithmTemplate(
        metadata=ObjectMeta(name=name, namespace=NS),
        spec=NexusAlgorithmSpec(
            container=Container(image="algo", registry="r", version_tag="v1"),
            workgroup_ref=WorkgroupRef(
                name=workgroup,
                group="science.sneaksanddata.com",
                kind="NexusAlgorithmWorkgroup",
            ),
        ),
    )


def make_workgroup(name, cluster="", capabilities=None):
    return NexusAlgorithmWorkgroup(
        metadata=ObjectMeta(name=name, namespace=NS),
        spec=NexusAlgorithmWorkgroupSpec(
            description="pool",
            cluster=cluster,
            capabilities=dict(capabilities or {}),
        ),
    )


class Fixture:
    def __init__(self):
        self.controller_store = ClusterStore("controller")
        self.shard_stores = {n: ClusterStore(n) for n in SHARD_CAPS}
        self.shards = [
            Shard(ALIAS, n, s, capabilities=SHARD_CAPS[n])
            for n, s in self.shard_stores.items()
        ]
        self.recorder = FakeRecorder()
        self.controller = Controller(
            self.controller_store,
            self.shards,
            recorder=self.recorder,
            statsd=StatsdClient("test"),
        )

    def seed(self, *objs):
        self.controller_store.seed(*objs)
        listers = {
            NexusAlgorithmTemplate.KIND: self.controller.template_lister,
            NexusAlgorithmWorkgroup.KIND: self.controller.workgroup_lister,
        }
        for obj in objs:
            stored = self.controller_store.get(
                obj.KIND, obj.metadata.namespace, obj.metadata.name
            )
            listers[obj.KIND].add(stored)

    def placed_on(self, name):
        """Shard names whose store holds template ``name``."""
        return sorted(
            n
            for n, s in self.shard_stores.items()
            if s.list(NexusAlgorithmTemplate.KIND)
            and any(
                t.metadata.name == name
                for t in s.list(NexusAlgorithmTemplate.KIND)
            )
        )


# ------------------------------------------------------------ unit: selector


def test_select_all_without_workgroup():
    f = Fixture()
    assert select_shards(make_template(), None, f.shards) == f.shards


def test_select_by_cluster():
    f = Fixture()
    wg = make_workgroup("wg", cluster="pool-v5p-a")
    assert [s.name for s in select_shards(make_template(), wg, f.shards)] == [
        "pool-v5p-a"
    ]


def test_select_by_capabilities():
    f = Fixture()
    wg = make_workgroup("wg", capabilities={"tpu-v5p": True, "moe": True})
    assert [s.name for s in select_shards(make_template(), wg, f.shards)] == [
        "pool-v5p-a",
        "pool-v5p-b",
    ]


def test_false_capabilities_are_not_required():
    f = Fixture()
    wg = make_workgroup("wg", capabilities={"tpu-v5e": True, "moe": False})
    assert [s.name for s in select_shards(make_template(), wg, f.shards)] == [
        "pool-v5e"
    ]


def test_unsatisfiable_cluster_raises():
    f = Fixture()
    wg = make_workgroup("wg", cluster="no-such-pool")
    with pytest.raises(PlacementError):
        select_shards(make_template(), wg, f.shards)


def test_unsatisfiable_capabilities_raises():
    f = Fixture()
    wg = make_workgroup("wg", capabilities={"tpu-v7x": True})
    with pytest.raises(PlacementError):
        select_shards(make_template(), wg, f.shards)


# ----------------------------------------------------- integration: reconcile


def test_template_without_workgroup_fans_out_everywhere():
    f = Fixture()
    f.seed(make_template("algo-all"))
    f.controller.template_sync_handler(NS, "algo-all")
    assert f.placed_on("algo-all") == sorted(SHARD_CAPS)


def test_moe_template_placed_on_two_matching_pools():
    """The config #5 scenario: MoE fan-out across exactly the two v5p pools."""
    f = Fixture()
    f.seed(
        make_workgroup("moe-pool", capabilities={"tpu-v5p": True, "moe": True}),
        make_template("mixtral", workgroup="moe-pool"),
    )
    f.controller.template_sync_handler(NS, "mixtral")
    assert f.placed_on("mixtral") == ["pool-v5p-a", "pool-v5p-b"]

    tmpl = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "mixtral")
    assert tmpl.status.synced_to_clusters == ["pool-v5p-a", "pool-v5p-b"]


def test_cluster_pinned_template_lands_on_one_pool():
    f = Fixture()
    f.seed(
        make_workgroup("edge", cluster="pool-v5e"),
        make_template("serving", workgroup="edge"),
    )
    f.controller.template_sync_handler(NS, "serving")
    assert f.placed_on("serving") == ["pool-v5e"]


def test_missing_workgroup_falls_back_to_all_shards():
    f = Fixture()
    f.seed(make_template("algo-x", workgroup="not-synced-yet"))
    f.controller.template_sync_handler(NS, "algo-x")
    assert f.placed_on("algo-x") == sorted(SHARD_CAPS)


def test_narrowing_placement_removes_stale_copies():
    """Template fans out everywhere before its workgroup syncs; when the
    workgroup appears and narrows placement, stale copies on unselected
    shards are deleted (only our own provenance-labelled copies)."""
    f = Fixture()
    f.seed(make_template("mixtral", workgroup="moe-pool"))
    f.controller.template_sync_handler(NS, "mixtral")
    assert f.placed_on("mixtral") == sorted(SHARD_CAPS)

    f.seed(make_workgroup("moe-pool", capabilities={"moe": True}))
    f.controller.template_sync_handler(NS, "mixtral")
    assert f.placed_on("mixtral") == ["pool-v5p-a", "pool-v5p-b"]
    tmpl = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "mixtral")
    assert tmpl.status.synced_to_clusters == ["pool-v5p-a", "pool-v5p-b"]


def test_narrowing_leaves_foreign_templates_alone():
    """A same-named template on an unselected shard that we did NOT write
    (no provenance label) must not be deleted."""
    f = Fixture()
    foreign = make_template("mixtral")
    f.shard_stores["pool-v5e"].seed(foreign)
    f.shards[0].template_lister.add(
        f.shard_stores["pool-v5e"].get(NexusAlgorithmTemplate.KIND, NS, "mixtral")
    )
    f.seed(
        make_workgroup("moe-pool", capabilities={"moe": True}),
        make_template("mixtral", workgroup="moe-pool"),
    )
    f.controller.template_sync_handler(NS, "mixtral")
    assert "pool-v5e" in f.placed_on("mixtral")  # foreign copy untouched
    assert f.placed_on("mixtral") == sorted(SHARD_CAPS)
    tmpl = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "mixtral")
    assert tmpl.status.synced_to_clusters == ["pool-v5p-a", "pool-v5p-b"]


def test_workgroup_event_reenqueues_referencing_templates():
    f = Fixture()
    f.seed(
        make_template("mixtral", workgroup="moe-pool"),
        make_template("other", workgroup="different-pool"),
    )
    wg = make_workgroup("moe-pool", capabilities={"moe": True})
    f.controller._handle_workgroup_event(wg)
    queued = set()
    while True:
        item, shutdown = f.controller.work_queue.get(timeout=0.1)
        if item is None or shutdown:
            break
        queued.add((item.name, item.obj_type))
        f.controller.work_queue.done(item)
    assert ("moe-pool", "workgroup") in queued
    assert ("mixtral", "template") in queued
    assert ("other", "template") not in queued


def test_unsatisfiable_placement_errors_and_requeues():
    f = Fixture()
    f.seed(
        make_workgroup("ghost", cluster="gone-pool"),
        make_template("algo-g", workgroup="ghost"),
    )
    with pytest.raises(SyncError):
        f.controller.template_sync_handler(NS, "algo-g")
    assert f.placed_on("algo-g") == []
    # a distinct ErrPlacement event (not the generic sync error) ...
    assert any(
        e.reason == REASON_ERR_PLACEMENT for e in f.recorder.events
    ), f.recorder.events
    # ... AND a Ready=False status condition carrying the reason, so the
    # template itself answers "why is this not running"
    from nexus_tpu.api.template import NexusAlgorithmTemplate

    stored = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-g")
    cond = stored.status.conditions[0]
    assert cond.status == "False"
    assert "Placement failed" in cond.message
    assert "gone-pool" in cond.message
