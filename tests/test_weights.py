"""Pretrained-weight ingestion (runtime/weights.py) + tokenizer
(utils/tokenizer.py): the literal "Llama-3-8B inference" path of BASELINE
config #3, tested against synthetic HF-format checkpoints (zero-egress
environment — real checkpoints can't be fetched, so parity is proven by
exporting our own params to the HF layout and converting back)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nexus_tpu.models import llama
from nexus_tpu.runtime.weights import (
    CheckpointReader,
    SafetensorsFile,
    convert_hf_llama,
    export_hf_llama,
    load_pretrained,
)


@pytest.fixture(scope="module")
def tiny_cfg():
    return llama.config("tiny", dtype=jnp.float32)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return llama.init(jax.random.PRNGKey(0), tiny_cfg)


def test_safetensors_roundtrip_exact_logits(tmp_path, tiny_cfg, tiny_params):
    """export → convert must reproduce the EXACT params (and logits)."""
    path = str(tmp_path / "model.safetensors")
    export_hf_llama(tiny_params, tiny_cfg, path)
    restored = convert_hf_llama(path, tiny_cfg)

    ref_leaves = {
        jax.tree_util.keystr(kp): v
        for kp, v in jax.tree_util.tree_leaves_with_path(tiny_params)
    }
    got_leaves = {
        jax.tree_util.keystr(kp): v
        for kp, v in jax.tree_util.tree_leaves_with_path(restored)
    }
    assert set(ref_leaves) == set(got_leaves)
    for k, ref in ref_leaves.items():
        np.testing.assert_array_equal(
            np.asarray(got_leaves[k]), np.asarray(ref), err_msg=k
        )

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, tiny_cfg.vocab_size, jnp.int32
    )
    ref_logits = llama.forward(tiny_params, tiny_cfg, tokens)
    got_logits = llama.forward(restored, tiny_cfg, tokens)
    np.testing.assert_array_equal(
        np.asarray(got_logits), np.asarray(ref_logits)
    )


def test_convert_places_on_mesh(tmp_path, tiny_cfg, tiny_params):
    """With a mesh + logical tree, converted leaves land sharded."""
    from nexus_tpu.parallel.mesh import MeshPlan, build_mesh

    path = str(tmp_path / "model.safetensors")
    export_hf_llama(tiny_params, tiny_cfg, path)
    mesh = build_mesh(MeshPlan(fsdp=4, tensor=2))
    params = load_pretrained(
        "llama", path, tiny_cfg, mesh=mesh,
        logical_tree=llama.logical_axes(tiny_cfg),
    )
    # embed: ('vocab','embed') → P('tensor','fsdp')
    sh = params["embed"].sharding
    assert set(sh.device_set) == set(mesh.devices.flat)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, tiny_cfg.vocab_size, jnp.int32
    )
    with mesh:
        logits = jax.jit(lambda p, t: llama.forward(p, tiny_cfg, t))(
            params, tokens
        )
    ref = llama.forward(tiny_params, tiny_cfg, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_convert_tied_embeddings(tmp_path, tiny_cfg, tiny_params):
    """Checkpoints without lm_head.weight (Llama-3.2 style) tie to embed."""
    from safetensors.numpy import load_file, save_file

    path = str(tmp_path / "model.safetensors")
    export_hf_llama(tiny_params, tiny_cfg, path)
    tensors = load_file(path)
    tensors.pop("lm_head.weight")
    save_file(tensors, path)
    restored = convert_hf_llama(path, tiny_cfg)
    np.testing.assert_array_equal(
        np.asarray(restored["lm_head"]),
        np.asarray(restored["embed"]).T,
    )


def test_convert_sharded_index_checkpoint(tmp_path, tiny_cfg, tiny_params):
    """model.safetensors.index.json weight_map over multiple shard files."""
    from safetensors.numpy import load_file, save_file

    single = str(tmp_path / "all.safetensors")
    export_hf_llama(tiny_params, tiny_cfg, single)
    tensors = load_file(single)
    names = sorted(tensors)
    half = len(names) // 2
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    save_file(
        {n: tensors[n] for n in names[:half]},
        str(ckpt / "model-00001-of-00002.safetensors"),
    )
    save_file(
        {n: tensors[n] for n in names[half:]},
        str(ckpt / "model-00002-of-00002.safetensors"),
    )
    weight_map = {
        n: ("model-00001-of-00002.safetensors" if i < half
            else "model-00002-of-00002.safetensors")
        for i, n in enumerate(names)
    }
    (ckpt / "model.safetensors.index.json").write_text(
        json.dumps({"weight_map": weight_map})
    )
    restored = convert_hf_llama(str(ckpt), tiny_cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, tiny_cfg.vocab_size, jnp.int32
    )
    np.testing.assert_array_equal(
        np.asarray(llama.forward(restored, tiny_cfg, tokens)),
        np.asarray(llama.forward(tiny_params, tiny_cfg, tokens)),
    )


def test_convert_rejects_mismatched_config(tmp_path, tiny_cfg, tiny_params):
    path = str(tmp_path / "model.safetensors")
    export_hf_llama(tiny_params, tiny_cfg, path)
    bad_layers = llama.config("tiny", n_layers=tiny_cfg.n_layers + 2,
                              dtype=jnp.float32)
    with pytest.raises(ValueError, match="n_layers"):
        convert_hf_llama(path, bad_layers)
    bad_width = llama.config("tiny", d_model=tiny_cfg.d_model * 2,
                             dtype=jnp.float32)
    with pytest.raises(ValueError, match="shape"):
        convert_hf_llama(path, bad_width)


def test_bf16_tensors_decode(tmp_path):
    """BF16 safetensors (the dtype real Llama checkpoints ship in) decode
    via ml_dtypes through the stdlib parser."""
    import ml_dtypes
    from safetensors.numpy import save_file

    x = np.arange(32, dtype=np.float32).reshape(4, 8)
    path = str(tmp_path / "bf16.safetensors")
    save_file({"t": x.astype(ml_dtypes.bfloat16)}, path)
    sf = SafetensorsFile(path)
    got = sf.tensor("t")
    assert got.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got.astype(np.float32), x)


def test_checkpoint_reader_rejects_nonsense(tmp_path):
    with pytest.raises(FileNotFoundError):
        CheckpointReader(str(tmp_path / "nope"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(FileNotFoundError):
        CheckpointReader(str(empty))


# ------------------------------------------------------------- tokenizer


def _build_tokenizer_json(path: str) -> str:
    """A real (small) byte-level BPE tokenizer.json built with the HF
    `tokenizers` library from a tiny corpus — the exact file format
    Llama-3 checkpoints ship."""
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders, trainers

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False,
                                                 use_regex=True)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400, special_tokens=["<|begin_of_text|>", "<|eot_id|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = [
        "the quick brown fox jumps over the lazy dog",
        "TPU native frameworks shard attention over meshes",
        "hello world, hello tokens! 12345",
        "multi-cluster controllers reconcile templates",
    ]
    tok.train_from_iterator(corpus, trainer)
    tok.save(path)
    return path


def test_tokenizer_pure_matches_rust(tmp_path):
    """The pure-Python BPE must agree with the Rust engine token-for-token
    on in-domain and out-of-domain text."""
    from nexus_tpu.utils.tokenizer import load_tokenizer

    path = _build_tokenizer_json(str(tmp_path / "tokenizer.json"))
    rust = load_tokenizer(path, engine="rust")
    pure = load_tokenizer(path, engine="pure")
    samples = [
        "the quick brown fox",
        "hello world",
        "unseen wörds — with ünïcode! 67890",
        "  leading spaces\nand newlines\n\n",
        "",
    ]
    for s in samples:
        assert pure.encode(s) == rust.encode(s), s


def test_tokenizer_roundtrip_and_special_tokens(tmp_path):
    from nexus_tpu.utils.tokenizer import load_tokenizer

    path = _build_tokenizer_json(str(tmp_path / "tokenizer.json"))
    pure = load_tokenizer(path, engine="pure")
    text = "hello world, the quick fox"
    assert pure.decode(pure.encode(text)) == text
    # special tokens match as whole pieces
    with open(path) as f:
        doc = json.load(f)
    bos = next(
        t for t in doc["added_tokens"]
        if t["content"] == "<|begin_of_text|>"
    )
    ids = pure.encode("<|begin_of_text|>hello")
    assert ids[0] == bos["id"]
    assert pure.decode(ids) == "<|begin_of_text|>hello"


def test_infer_runtime_with_pretrained_weights_and_prompt(tmp_path):
    """End-to-end config #3 shape: an infer template pointing at a
    safetensors checkpoint + tokenizer decodes a TEXT prompt with the
    converted weights and reports a text completion."""
    from nexus_tpu.api.runtime_spec import (
        InferSpec,
        JaxXlaRuntime,
        ModelRef,
        ParallelismSpec,
        TpuSliceSpec,
        TrainSpec,
        WeightsSpec,
    )
    from nexus_tpu.runtime.entrypoints import run_template_runtime

    cfg = llama.config("tiny", dtype=jnp.float32)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    ckpt = str(tmp_path / "model.safetensors")
    export_hf_llama(params, cfg, ckpt)
    tok_path = _build_tokenizer_json(str(tmp_path / "tokenizer.json"))

    runtime = JaxXlaRuntime(
        mode="infer",
        model=ModelRef(
            family="llama", preset="tiny",
            overrides={"dtype": "float32"},
            weights=WeightsSpec(path=ckpt, tokenizer=tok_path),
        ),
        tpu=TpuSliceSpec(accelerator="v5e", topology="2x4", slice_count=1),
        parallelism=ParallelismSpec(data=2, fsdp=2, tensor=2),
        train=TrainSpec(batch_size=2, seq_len=32),
        infer=InferSpec(
            prompt="the quick brown fox", max_new_tokens=8, iterations=1
        ),
    )
    assert runtime.validate() == []
    metrics = run_template_runtime(runtime)
    assert metrics["weights_loaded"] is True
    assert metrics["prompt_tokens"] > 0
    assert isinstance(metrics["completion"], str)
    assert metrics["decode_tokens_per_sec"] > 0


def test_weights_spec_validation():
    from nexus_tpu.api.runtime_spec import (
        JaxXlaRuntime,
        ModelRef,
        WeightsSpec,
    )

    rt = JaxXlaRuntime(
        mode="infer",
        model=ModelRef(family="mlp", preset="tiny",
                       weights=WeightsSpec(path="/x")),
    )
    errs = rt.validate()
    assert any("no safetensors converter" in e for e in errs)
    rt2 = JaxXlaRuntime(
        mode="infer",
        model=ModelRef(family="llama", preset="tiny",
                       weights=WeightsSpec(path="", format="gguf")),
    )
    errs2 = rt2.validate()
    assert any("format" in e for e in errs2)
    assert any("path" in e for e in errs2)


def test_gptneox_roundtrip_exact_logits(tmp_path):
    """export → convert reproduces exact gptneox logits, covering the
    fused query_key_value head-interleaving both directions."""
    from nexus_tpu.models import gptneox
    from nexus_tpu.runtime.weights import (
        convert_hf_gptneox,
        export_hf_gptneox,
    )

    cfg = gptneox.config("tiny", dtype=jnp.float32)
    params = gptneox.init(jax.random.PRNGKey(3), cfg)
    path = str(tmp_path / "model.safetensors")
    export_hf_gptneox(params, cfg, path)
    restored = convert_hf_gptneox(path, cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size, jnp.int32
    )
    np.testing.assert_array_equal(
        np.asarray(gptneox.forward(restored, cfg, tokens)),
        np.asarray(gptneox.forward(params, cfg, tokens)),
    )


def test_neox_qkv_interleave_roundtrip():
    """The de-interleave is the exact inverse of the interleave, and
    de-interleaving really reorders (not a no-op)."""
    from nexus_tpu.runtime.weights import (
        _deinterleave_neox_qkv,
        _interleave_neox_qkv,
    )

    h, hd, d = 4, 8, 32
    w = np.arange(3 * h * hd * d, dtype=np.float32).reshape(3 * h * hd, d)
    de = _deinterleave_neox_qkv(w, h, hd)
    assert not np.array_equal(de, w)
    np.testing.assert_array_equal(_interleave_neox_qkv(de, h, hd), w)
    b = np.arange(3 * h * hd, dtype=np.float32)
    np.testing.assert_array_equal(
        _interleave_neox_qkv(_deinterleave_neox_qkv(b, h, hd), h, hd), b
    )


def test_mixtral_roundtrip_exact_logits(tmp_path):
    """export → convert reproduces exact mixtral logits (per-expert HF
    w1/w2/w3 naming, fp32 router transposed from gate.weight)."""
    from nexus_tpu.models import mixtral
    from nexus_tpu.runtime.weights import (
        convert_hf_mixtral,
        export_hf_mixtral,
    )

    cfg = mixtral.config("tiny", dtype=jnp.float32)
    params = mixtral.init(jax.random.PRNGKey(5), cfg)
    path = str(tmp_path / "model.safetensors")
    export_hf_mixtral(params, cfg, path)
    restored = convert_hf_mixtral(path, cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size, jnp.int32
    )
    got_logits, _ = mixtral.forward(restored, cfg, tokens)
    ref_logits, _ = mixtral.forward(params, cfg, tokens)
    np.testing.assert_array_equal(
        np.asarray(got_logits), np.asarray(ref_logits)
    )


def test_weights_spec_now_validates_all_lm_families():
    from nexus_tpu.api.runtime_spec import (
        JaxXlaRuntime,
        ModelRef,
        WeightsSpec,
    )

    for family in ("llama", "gptneox", "mixtral"):
        rt = JaxXlaRuntime(
            mode="infer",
            model=ModelRef(family=family, preset="tiny",
                           weights=WeightsSpec(path="/x")),
        )
        assert not any(
            "no safetensors converter" in e for e in rt.validate()
        ), family


def test_build_corpus_roundtrip(tmp_path):
    """tools/build_corpus.py: text -> tokenizer.json BPE -> binary corpus
    that token_file_batches (and thus the native reader) consumes, and
    decoding the corpus recovers the text."""
    from nexus_tpu.train.data import TOKEN_DTYPES, token_file_batches
    from nexus_tpu.utils.tokenizer import load_tokenizer
    from tools.build_corpus import build_corpus

    tok_path = _build_tokenizer_json(str(tmp_path / "tokenizer.json"))
    docs = [
        "the quick brown fox jumps over the lazy dog",
        "hello world, hello tokens",
    ]
    for i, d in enumerate(docs):
        (tmp_path / f"doc{i}.txt").write_text(d)
    out = str(tmp_path / "corpus.bin")
    total = build_corpus(
        [str(tmp_path / f"doc{i}.txt") for i in range(len(docs))],
        tok_path, out, dtype="uint16",
    )
    assert total > 0
    raw = np.fromfile(out, dtype=TOKEN_DTYPES["uint16"])
    assert len(raw) == total
    tok = load_tokenizer(tok_path)
    assert tok.decode([int(t) for t in raw]) == "".join(docs)

    # the training reader consumes it (seq_len+1 windows)
    batch = next(token_file_batches(out, batch_size=2, seq_len=8,
                                    dtype="uint16"))
    assert batch["tokens"].shape == (2, 9)

    # dtype overflow is caught loudly, not wrapped silently
    with pytest.raises(ValueError, match="exceeds dtype"):
        build_corpus(
            [str(tmp_path / "doc0.txt")], tok_path,
            str(tmp_path / "c2.bin"), dtype="uint16", separator_id=70000,
        )
