"""Pod-side worker entrypoint (runtime/worker.py): process-identity math,
env-contract parsing, single-process execution, and the materializer's
default command wiring."""

import json

import pytest

from nexus_tpu.api.runtime_spec import (
    JaxXlaRuntime,
    ModelRef,
    ParallelismSpec,
    TpuSliceSpec,
    TrainSpec,
)
from nexus_tpu.runtime.materializer import materialize_job
from nexus_tpu.runtime.worker import (
    WorkerIdentity,
    identity_from_env,
    maybe_initialize_distributed,
    run_from_env,
)
from tests.test_runtime import template_with_runtime


def test_process_identity_grid():
    # 2 slices × 4 hosts: coordinator is (0,0) → process 0; slices are
    # contiguous host blocks
    ids = [
        WorkerIdentity(s, 2, h, 4).process_id for s in range(2) for h in range(4)
    ]
    assert ids == list(range(8))
    assert WorkerIdentity(1, 2, 3, 4).num_processes == 8


def test_identity_from_env_derives_from_indexed_job():
    rt = JaxXlaRuntime(
        tpu=TpuSliceSpec(accelerator="v5p", topology="2x2x4", slice_count=2)
    )  # 16 chips/slice, 4 chips/host → 4 hosts/slice
    env = {
        "NEXUS_SLICE_INDEX": "1",
        "NEXUS_SLICE_COUNT": "2",
        "JOB_COMPLETION_INDEX": "2",
    }
    ident = identity_from_env(rt, env)
    assert ident.hosts_per_slice == 4
    assert ident.process_id == 6
    assert ident.num_processes == 8


def test_single_process_skips_distributed_init():
    ident = WorkerIdentity(0, 1, 0, 1)
    assert maybe_initialize_distributed(ident, {}) is False


def test_multi_process_requires_coordinator():
    ident = WorkerIdentity(0, 2, 0, 4)
    with pytest.raises(RuntimeError, match="JAX_COORDINATOR_ADDRESS"):
        maybe_initialize_distributed(ident, {})


def test_run_from_env_requires_spec():
    with pytest.raises(RuntimeError, match="NEXUS_RUNTIME_SPEC"):
        run_from_env({})


def test_run_from_env_executes_runtime():
    rt = JaxXlaRuntime(
        mode="train",
        model=ModelRef(family="mlp", preset="tiny"),
        tpu=TpuSliceSpec(accelerator="v5e", topology="1x1", slice_count=1),
        parallelism=ParallelismSpec(),
        train=TrainSpec(batch_size=8, steps=2, learning_rate=1e-2),
    )
    env = {
        "NEXUS_RUNTIME_SPEC": json.dumps(rt.to_dict()),
        "NEXUS_SHARD_NAME": "shard-a",
    }
    metrics = run_from_env(env)
    assert metrics["mode"] == "train"
    assert metrics["steps"] == 2
    assert metrics["shard"] == "shard-a"
    assert metrics["process_id"] == 0
    assert metrics["distributed"] is False


def test_run_from_env_rejects_invalid_spec():
    rt = JaxXlaRuntime(
        parallelism=ParallelismSpec(data=3),  # 3 != 1 chip
        tpu=TpuSliceSpec(accelerator="v5e", topology="1x1"),
    )
    with pytest.raises(RuntimeError, match="invalid runtime spec"):
        run_from_env({"NEXUS_RUNTIME_SPEC": json.dumps(rt.to_dict())})


def test_materializer_defaults_command_to_worker_module():
    def command_of(tmpl):
        job = materialize_job(tmpl)[0]
        return job["spec"]["template"]["spec"]["containers"][0]["command"]

    tmpl = template_with_runtime()
    tmpl.spec.command = ""
    tmpl.spec.args = []
    assert command_of(tmpl) == ["python", "-m", "nexus_tpu.runtime.worker"]

    tmpl2 = template_with_runtime()
    tmpl2.spec.command = "/custom/entrypoint"
    assert command_of(tmpl2) == ["/custom/entrypoint"]

    # args without command target the image's own ENTRYPOINT — no default
    tmpl3 = template_with_runtime()
    tmpl3.spec.command = ""
    tmpl3.spec.args = ["--my-flag"]
    assert command_of(tmpl3) is None
