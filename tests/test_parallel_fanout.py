"""Parallel shard fan-out + write-skip cache semantics.

Covers the concurrency contract of the reconcile hot path
(docs/reconciler-concurrency.md):
  * per-shard work genuinely runs concurrently on the bounded executor;
  * first-error fail-fast → single aggregated SyncError → one rate-limited
    requeue;
  * no partial-write leaks: every object a failed fan-out did land on a
    shard carries complete provenance labels, and failed shards are
    untouched;
  * write-skip cache: hit on unchanged re-sync, miss on source change,
    invalidation on shard-side drift and rogue/adoption;
  * workqueue burst coalescing counters (python + native backends).
"""

import threading

import pytest

from nexus_tpu.api.template import (
    ComputeResources,
    Container,
    NexusAlgorithmSpec,
    NexusAlgorithmTemplate,
    RuntimeEnvironment,
    WorkgroupRef,
)
from nexus_tpu.api.types import (
    CONTROLLER_APP_NAME,
    EnvFromSource,
    LABEL_CONFIGURATION_OWNER,
    LABEL_CONTROLLER_APP,
    ObjectMeta,
    Secret,
)
from nexus_tpu.cluster.store import ClusterStore
from nexus_tpu.controller.controller import (
    Controller,
    Element,
    SyncError,
    TYPE_TEMPLATE,
)
from nexus_tpu.controller.events import FakeRecorder
from nexus_tpu.controller.sharding import (
    ShardFanOutError,
    ShardSyncExecutor,
    WriteSkipCache,
    stable_hash,
)
from nexus_tpu.shards.shard import Shard
from nexus_tpu.utils.telemetry import METRIC_SHARD_SYNC_LATENCY, StatsdClient

NS = "nexus"
ALIAS = "fanout-cluster"


def make_template(name="algo-1", secrets=()):
    mapped = [EnvFromSource(secret_ref=s) for s in secrets]
    return NexusAlgorithmTemplate(
        metadata=ObjectMeta(name=name, namespace=NS),
        spec=NexusAlgorithmSpec(
            container=Container(
                image="algo", registry="ghcr.io/test", version_tag="v1.0.0",
                service_account_name="nexus-sa",
            ),
            compute_resources=ComputeResources(cpu_limit="4", memory_limit="8Gi"),
            workgroup_ref=WorkgroupRef(
                name="wg-1", group="science.sneaksanddata.com",
                kind="NexusAlgorithmWorkgroup",
            ),
            command="python",
            args=["run.py"],
            runtime_environment=RuntimeEnvironment(
                mapped_environment_variables=mapped
            ),
        ),
    )


def make_secret(name="secret-1", data=None):
    return Secret(metadata=ObjectMeta(name=name, namespace=NS),
                  data=dict(data or {"key": "value"}))


class Fixture:
    def __init__(self, n_shards=3, shard_sync_workers=0):
        self.controller_store = ClusterStore("controller")
        self.shard_stores = [ClusterStore(f"shard{i}") for i in range(n_shards)]
        self.shards = [
            Shard(ALIAS, f"shard{i}", s) for i, s in enumerate(self.shard_stores)
        ]
        self.recorder = FakeRecorder()
        self.statsd = StatsdClient("test")
        self.controller = Controller(
            self.controller_store,
            self.shards,
            recorder=self.recorder,
            statsd=self.statsd,
            use_finalizers=False,
            shard_sync_workers=shard_sync_workers,
        )

    def seed_controller(self, *objs):
        self.controller_store.seed(*objs)
        c = self.controller
        listers = {
            NexusAlgorithmTemplate.KIND: c.template_lister,
            Secret.KIND: c.secret_lister,
        }
        for obj in objs:
            stored = self.controller_store.get(
                obj.KIND, obj.metadata.namespace, obj.metadata.name
            )
            listers[obj.KIND].add(stored)

    def resync_listers(self):
        c = self.controller
        for kind, lister in (
            (NexusAlgorithmTemplate.KIND, c.template_lister),
            (Secret.KIND, c.secret_lister),
        ):
            for obj in self.controller_store.list(kind):
                lister.add(obj)
        for shard, store in zip(self.shards, self.shard_stores):
            for kind, lister in (
                (NexusAlgorithmTemplate.KIND, shard.template_lister),
                (Secret.KIND, shard.secret_lister),
            ):
                for obj in store.list(kind):
                    lister.add(obj)

    def clear_actions(self):
        self.controller_store.clear_actions()
        for s in self.shard_stores:
            s.clear_actions()


# ------------------------------------------------------------------ executor


def test_executor_sequential_fail_fast_stops_at_first_error():
    ex = ShardSyncExecutor(max_workers=1)

    class S:
        def __init__(self, name):
            self.name = name

    calls = []

    def fn(shard):
        calls.append(shard.name)
        if shard.name == "s1":
            raise RuntimeError("boom")

    with pytest.raises(ShardFanOutError) as ei:
        ex.map_shards([S("s0"), S("s1"), S("s2")], fn)
    # sequential: s2 never started after s1 failed
    assert calls == ["s0", "s1"]
    assert ei.value.errors[0][0] == "s1"
    assert isinstance(ei.value.first, RuntimeError)


def test_executor_parallel_aggregates_errors_in_shard_order():
    ex = ShardSyncExecutor(max_workers=4)

    class S:
        def __init__(self, name):
            self.name = name

    def fn(shard):
        if shard.name in ("s1", "s3"):
            raise RuntimeError(f"{shard.name} down")
        return shard.name

    # fail_fast=False attempts every shard: both errors aggregate in
    # input-shard order regardless of completion order
    with pytest.raises(ShardFanOutError) as ei:
        ex.map_shards([S(f"s{i}") for i in range(4)], fn, fail_fast=False)
    assert [name for name, _ in ei.value.errors] == ["s1", "s3"]
    assert "s1 down" in str(ei.value)

    # fail_fast=True: at least the first error surfaces; siblings that had
    # not started yet are cooperatively skipped, never silently succeed
    with pytest.raises(ShardFanOutError) as ei:
        ex.map_shards([S(f"s{i}") for i in range(4)], fn)
    assert ei.value.errors[0][0] in ("s1", "s3")
    ex.shutdown()


def test_executor_results_preserve_input_order():
    ex = ShardSyncExecutor(max_workers=4)

    class S:
        def __init__(self, name, delay):
            self.name = name
            self.delay = delay

    import time

    def fn(shard):
        time.sleep(shard.delay)
        return shard.name

    # slowest first: completion order inverts input order
    shards = [S("a", 0.05), S("b", 0.02), S("c", 0.0)]
    assert ex.map_shards(shards, fn) == ["a", "b", "c"]
    ex.shutdown()


def test_fan_out_runs_concurrently():
    """All shards must be in-flight simultaneously: each shard's create
    blocks on a barrier that only opens when every shard has arrived."""
    f = Fixture(n_shards=3)
    f.seed_controller(make_template())
    barrier = threading.Barrier(3, timeout=5.0)

    originals = [s.create_template for s in f.shards]

    def make_blocked(orig):
        def blocked(name, namespace, spec, field_manager=""):
            barrier.wait()  # raises BrokenBarrierError if run sequentially
            return orig(name, namespace, spec, field_manager)

        return blocked

    for shard, orig in zip(f.shards, originals):
        shard.create_template = make_blocked(orig)

    f.controller.template_sync_handler(NS, "algo-1")
    for store in f.shard_stores:
        assert store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
    # per-shard latency gauges emitted for every shard
    shard_tags = {
        tags for (name, _v, tags) in f.statsd.history
        if name == f"test.{METRIC_SHARD_SYNC_LATENCY}"
    }
    assert {("shard:shard0",), ("shard:shard1",), ("shard:shard2",)} <= shard_tags


# ------------------------------------------------------- fail-fast semantics


def test_fanout_failure_raises_single_sync_error_and_requeues():
    f = Fixture(n_shards=3)
    f.seed_controller(make_template())

    def broken(*a, **k):
        raise RuntimeError("shard1 unreachable")

    f.shards[1].create_template = broken

    with pytest.raises(SyncError) as ei:
        f.controller.template_sync_handler(NS, "algo-1")
    assert "shard1" in str(ei.value)

    # through the work loop: failure → one rate-limited requeue
    item = Element(NS, "algo-1", TYPE_TEMPLATE)
    f.controller.work_queue.add(item)
    assert f.controller.process_next_work_item(timeout=1.0)
    assert f.controller.work_queue.num_requeues(item) == 1


def test_fanout_failure_no_partial_provenance_leaks():
    """Shards that did receive writes before a sibling failed must carry
    COMPLETE provenance labels; the failed shard stays untouched."""
    f = Fixture(n_shards=3)
    f.seed_controller(make_template(secrets=["secret-1"]), make_secret())

    def broken(*a, **k):
        raise RuntimeError("shard2 unreachable")

    f.shards[2].create_template = broken

    with pytest.raises(SyncError):
        f.controller.template_sync_handler(NS, "algo-1")

    expected = {
        LABEL_CONTROLLER_APP: CONTROLLER_APP_NAME,
        LABEL_CONFIGURATION_OWNER: ALIAS,
    }
    for store in f.shard_stores[:2]:
        for kind in (NexusAlgorithmTemplate.KIND, Secret.KIND):
            for obj in store.list(kind, NS):
                assert obj.metadata.labels == expected
    assert f.shard_stores[2].list(NexusAlgorithmTemplate.KIND, NS) == []
    assert f.shard_stores[2].list(Secret.KIND, NS) == []

    # the template was NOT reported synced anywhere
    ctrl = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
    assert ctrl.status.synced_to_clusters == []

    # heal the shard → retry converges everywhere
    f.shards[2].create_template = Shard.create_template.__get__(f.shards[2])
    f.resync_listers()
    f.controller.template_sync_handler(NS, "algo-1")
    for store in f.shard_stores:
        assert store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
    ctrl = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
    assert ctrl.status.synced_to_clusters == ["shard0", "shard1", "shard2"]


# --------------------------------------------------------- write-skip cache


def test_write_skip_hit_on_unchanged_resync():
    f = Fixture(n_shards=2)
    f.seed_controller(make_template(secrets=["secret-1"]), make_secret())
    f.controller.template_sync_handler(NS, "algo-1")
    f.resync_listers()
    f.clear_actions()

    before = f.controller.write_skip_cache.stats()
    f.controller.template_sync_handler(NS, "algo-1")
    after = f.controller.write_skip_cache.stats()

    assert f.controller_store.actions == []
    for store in f.shard_stores:
        assert store.actions == []
    # per shard: template + secret = 2 hits x 2 shards
    assert after["hits"] - before["hits"] == 4


def test_write_skip_miss_on_source_content_change():
    f = Fixture(n_shards=1)
    f.seed_controller(make_template(secrets=["secret-1"]), make_secret())
    f.controller.template_sync_handler(NS, "algo-1")
    f.resync_listers()

    sec = f.controller_store.get(Secret.KIND, NS, "secret-1")
    sec.data = {"key": "CHANGED"}
    f.controller_store.update(sec)
    f.resync_listers()
    f.clear_actions()

    f.controller.template_sync_handler(NS, "algo-1")
    assert f.shard_stores[0].get(Secret.KIND, NS, "secret-1").data == {
        "key": "CHANGED"
    }


def test_write_skip_invalidated_on_shard_drift():
    """Out-of-band shard edit bumps the shard resourceVersion → the cached
    entry no longer matches → full compare path repairs the drift."""
    f = Fixture(n_shards=1)
    f.seed_controller(make_template(secrets=["secret-1"]), make_secret())
    f.controller.template_sync_handler(NS, "algo-1")
    f.resync_listers()

    tampered = f.shard_stores[0].get(Secret.KIND, NS, "secret-1")
    tampered.data = {"key": "TAMPERED"}
    f.shard_stores[0].update(tampered)
    f.resync_listers()
    f.clear_actions()

    f.controller.template_sync_handler(NS, "algo-1")
    repaired = f.shard_stores[0].get(Secret.KIND, NS, "secret-1")
    assert repaired.data == {"key": "value"}


def test_write_skip_does_not_mask_rogue_detection():
    """A converged sync, then owner references stripped on the shard copy:
    the rv bump invalidates the hit and the rogue check must fire."""
    f = Fixture(n_shards=1)
    f.seed_controller(make_template(secrets=["secret-1"]), make_secret())
    f.controller.template_sync_handler(NS, "algo-1")
    f.resync_listers()

    shard_sec = f.shard_stores[0].get(Secret.KIND, NS, "secret-1")
    shard_sec.metadata.owner_references = []
    f.shard_stores[0].update(shard_sec)
    f.resync_listers()

    with pytest.raises(SyncError):
        f.controller.template_sync_handler(NS, "algo-1")
    # the rogue object's cache entries were dropped
    assert f.controller.write_skip_cache.stats()["invalidations"] >= 1


def test_write_skip_entries_are_owner_scoped():
    """Template A's converged entry for a shared secret must not let
    template B skip appending its own owner reference."""
    f = Fixture(n_shards=1)
    f.seed_controller(
        make_template("algo-1", secrets=["shared"]),
        make_template("algo-2", secrets=["shared"]),
        make_secret("shared"),
    )
    f.controller.template_sync_handler(NS, "algo-1")
    f.resync_listers()
    f.controller.template_sync_handler(NS, "algo-2")
    f.resync_listers()

    shard_sec = f.shard_stores[0].get(Secret.KIND, NS, "shared")
    t1 = f.shard_stores[0].get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
    t2 = f.shard_stores[0].get(NexusAlgorithmTemplate.KIND, NS, "algo-2")
    uids = {r.uid for r in shard_sec.metadata.owner_references}
    assert uids == {t1.metadata.uid, t2.metadata.uid}


def test_write_skip_invalidated_on_template_delete():
    f = Fixture(n_shards=2)
    f.seed_controller(make_template(secrets=["secret-1"]), make_secret())
    f.controller.template_sync_handler(NS, "algo-1")
    f.resync_listers()
    assert f.controller.write_skip_cache.stats()["entries"] > 0

    tmpl = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
    f.controller.handle_object_delete(tmpl)
    assert f.controller.write_skip_cache.stats()["entries"] == 0


def test_stable_hash_tracks_deep_equal():
    t1, t2 = make_template("a"), make_template("a")
    assert stable_hash(t1.spec) == stable_hash(t2.spec)
    t2.spec.container.version_tag = "v2.0.0"
    assert stable_hash(t1.spec) != stable_hash(t2.spec)
    assert stable_hash({"a": 1, "b": 2}) == stable_hash({"b": 2, "a": 1})
    assert stable_hash({"a": 1}) != stable_hash({"a": "1"})


def test_write_skip_cache_unit():
    c = WriteSkipCache()
    assert not c.check("s0", "Secret", NS, "x", "h1", "5", "uid-a")
    c.store("s0", "Secret", NS, "x", "h1", "5", "uid-a")
    assert c.check("s0", "Secret", NS, "x", "h1", "5", "uid-a")
    assert not c.check("s0", "Secret", NS, "x", "h2", "5", "uid-a")  # content
    assert not c.check("s0", "Secret", NS, "x", "h1", "6", "uid-a")  # rv
    assert not c.check("s0", "Secret", NS, "x", "h1", "5", "uid-b")  # owner
    c.invalidate_object("s0", "Secret", NS, "x")
    assert not c.check("s0", "Secret", NS, "x", "h1", "5", "uid-a")
    c.store("s0", "Secret", NS, "x", "h1", "5", "uid-a")
    c.store("s1", "Secret", NS, "x", "h1", "7", "uid-a")
    c.invalidate_owner("uid-a", "s1")
    assert c.check("s0", "Secret", NS, "x", "h1", "5", "uid-a")
    assert not c.check("s1", "Secret", NS, "x", "h1", "7", "uid-a")


def test_apply_job_converges_on_unlabeled_name_collision():
    """A foreign same-name Job without provenance labels is invisible to the
    label-filtered LIST; apply_job(existing=None) must fall back to a point
    GET and converge (delete+recreate) instead of requeue-looping on 409."""
    from nexus_tpu.api.workload import Job

    store = ClusterStore("shard0")
    shard = Shard(ALIAS, "shard0", store)
    foreign = Job.from_manifest({
        "metadata": {"name": "algo-s0", "namespace": NS},
        "spec": {"template": {"spec": {"containers": []}}},
    })
    store.create(foreign)  # no provenance labels

    owner = make_template()
    manifest = {
        "metadata": {"name": "algo-s0", "namespace": NS},
        "spec": {"template": {"spec": {"containers": [{"name": "c"}]}}},
    }
    applied = shard.apply_job(owner, manifest, "fm", existing=None)
    assert applied.spec == manifest["spec"]
    assert applied.metadata.labels[LABEL_CONTROLLER_APP] == CONTROLLER_APP_NAME


# ----------------------------------------------------------- queue coalescing


def test_python_workqueue_coalesces_duplicate_keys():
    from nexus_tpu.controller.ratelimit import default_controller_rate_limiter
    from nexus_tpu.controller.workqueue import RateLimitingQueue

    q = RateLimitingQueue(default_controller_rate_limiter(0.01, 1.0, 50, 100))
    for _ in range(5):
        q.add("k1")
    q.add("k2")
    assert q.depth() == 2
    assert q.coalesced_total() == 4
    # a key being processed coalesces re-adds into the dirty set, not a
    # second queue entry
    item, _ = q.get(timeout=1.0)
    q.add(item)
    q.add(item)  # second re-add while processing IS a coalesced duplicate
    assert q.coalesced_total() == 5
    q.shut_down()


def test_native_workqueue_coalesces_duplicate_keys():
    from nexus_tpu.native import NativeRateLimitingQueue, available

    if not available():
        pytest.skip("native queue unavailable")
    q = NativeRateLimitingQueue(0.01, 1.0, 50, 100)
    for _ in range(5):
        q.add("k1")
    q.add("k2")
    assert q.depth() == 2
    assert q.coalesced_total() == 4
    q.shut_down()
