"""Sharded training: FSDP+TP train step on the 8-device mesh, grad-accum
equivalence, checkpoint save/restore roundtrip."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from nexus_tpu.models import llama
from nexus_tpu.parallel.mesh import MeshPlan, build_mesh
from nexus_tpu.train.data import synthetic_lm_batches
from nexus_tpu.train.trainer import (
    TrainState,
    Trainer,
    build_optimizer,
    init_train_state,
    make_train_step,
)


def tiny_cfg():
    return llama.config("tiny", dtype=jnp.float32)


def test_sharded_fsdp_tp_train_step():
    """Full train step jitted over a (data=2, fsdp=2, tensor=2) mesh: params
    actually sharded (per-device shards smaller than global), loss finite,
    and a few steps reduce it."""
    cfg = tiny_cfg()
    mesh = build_mesh(MeshPlan(data=2, fsdp=2, tensor=2))
    opt = build_optimizer(learning_rate=1e-2, grad_clip=1.0)
    key = jax.random.PRNGKey(0)

    with mesh:
        state = init_train_state(
            lambda: llama.init(key, cfg), opt, mesh=mesh,
            logical_tree=llama.logical_axes(cfg),
        )
        # FSDP+TP sharding is real: embed (vocab×d) is split over tensor(vocab)
        # and fsdp(embed) → each device holds 1/4 of it
        embed = state.params["embed"]
        assert embed.sharding.spec == P("tensor", "fsdp")
        shard_shape = embed.addressable_shards[0].data.shape
        assert shard_shape == (cfg.vocab_size // 2, cfg.d_model // 2)

        step = make_train_step(
            lambda p, b: llama.loss_fn(p, cfg, b), opt, mesh=mesh
        )
        data = synthetic_lm_batches(8, 32, cfg.vocab_size, seed=0)
        losses = []
        for _ in range(10):
            state, metrics = step(state, next(data))
            losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_sharded_matches_single_device():
    """The sharded step computes the same math as the unsharded step."""
    cfg = tiny_cfg()
    opt = optax.sgd(1e-2)  # deterministic, no moments
    key = jax.random.PRNGKey(0)
    data = synthetic_lm_batches(8, 16, cfg.vocab_size, seed=3)
    batch = next(data)

    params = llama.init(key, cfg)
    state_single = TrainState(params, opt.init(params), jnp.zeros((), jnp.int32))
    step_single = make_train_step(lambda p, b: llama.loss_fn(p, cfg, b), opt)
    _, m_single = step_single(state_single, batch)

    mesh = build_mesh(MeshPlan(data=2, fsdp=2, tensor=2))
    with mesh:
        state_sharded = init_train_state(
            lambda: llama.init(key, cfg), opt, mesh=mesh,
            logical_tree=llama.logical_axes(cfg),
        )
        step_sharded = make_train_step(
            lambda p, b: llama.loss_fn(p, cfg, b), opt, mesh=mesh
        )
        _, m_sharded = step_sharded(state_sharded, batch)

    np.testing.assert_allclose(
        float(m_single["loss"]), float(m_sharded["loss"]), rtol=1e-4
    )


def test_grad_accum_equivalent_to_large_batch():
    cfg = tiny_cfg()
    opt = optax.sgd(1e-2)
    key = jax.random.PRNGKey(0)
    batch = next(synthetic_lm_batches(8, 16, cfg.vocab_size, seed=1))

    params = llama.init(key, cfg)

    def run(grad_accum):
        state = TrainState(params, opt.init(params), jnp.zeros((), jnp.int32))
        step = make_train_step(
            lambda p, b: llama.loss_fn(p, cfg, b), opt, grad_accum=grad_accum,
            donate=False,
        )
        new_state, _ = step(state, batch)
        return new_state.params

    p1 = run(1)
    p4 = run(4)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(np.array(a), np.array(b), rtol=2e-4, atol=2e-5)


def test_trainer_reports_throughput():
    cfg = tiny_cfg()
    opt = build_optimizer(learning_rate=1e-2)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    state = TrainState(params, opt.init(params), jnp.zeros((), jnp.int32))
    step = make_train_step(lambda p, b: llama.loss_fn(p, cfg, b), opt)
    trainer = Trainer(
        step, state, synthetic_lm_batches(4, 32, cfg.vocab_size),
        tokens_per_batch=4 * 32,
    )
    result = trainer.run(5)
    assert result.steps == 5
    assert result.tokens_per_sec > 0
    assert result.final_metrics["loss"] > 0
    assert len(result.loss_history) == 4  # first step is warmup


def test_trainer_run_ahead_depth():
    """Deeper run-ahead bounds in-flight work without changing results, and
    the CPU default stays 1 (deeper pipelining deadlocks the in-process
    collective communicator — trainer.py)."""
    cfg = tiny_cfg()
    opt = build_optimizer(learning_rate=1e-2)

    def fresh_state():
        params = llama.init(jax.random.PRNGKey(0), cfg)
        return TrainState(params, opt.init(params), jnp.zeros((), jnp.int32))

    step = make_train_step(
        lambda p, b: llama.loss_fn(p, cfg, b), opt, donate=False
    )

    def losses(run_ahead):
        trainer = Trainer(
            step, fresh_state(), synthetic_lm_batches(4, 32, cfg.vocab_size),
            tokens_per_batch=4 * 32, run_ahead=run_ahead,
        )
        return [float(l) for l in trainer.run(6).loss_history]

    from nexus_tpu.utils.hw import is_tpu

    default = Trainer(
        step, fresh_state(), synthetic_lm_batches(4, 32, cfg.vocab_size)
    )
    # backend-dependent default: CPU must stay at depth 1 (communicator
    # deadlock), TPU pipelines deeper
    assert default.run_ahead == (4 if is_tpu() else 1)
    np.testing.assert_allclose(losses(1), losses(3), rtol=1e-6)


def test_checkpoint_roundtrip(tmp_path):
    from nexus_tpu.train.checkpoint import Checkpointer

    cfg = tiny_cfg()
    opt = optax.adam(1e-3)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    state = TrainState(params, opt.init(params), jnp.asarray(7, jnp.int32))

    ckpt = Checkpointer(str(tmp_path / "ckpt"), keep=2)
    ckpt.save(state, wait=True)
    assert ckpt.latest_step() == 7

    # restore into zeros-shaped state
    zeros = jax.tree_util.tree_map(jnp.zeros_like, state)
    restored = ckpt.restore(zeros)
    ckpt.close()
    for a, b in zip(
        jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)
    ):
        np.testing.assert_array_equal(np.array(a), np.array(b))


def test_checkpoint_resume_continues_step(tmp_path):
    from nexus_tpu.train.checkpoint import Checkpointer

    cfg = tiny_cfg()
    opt = optax.adam(1e-3)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    state = TrainState(params, opt.init(params), jnp.asarray(0, jnp.int32))
    step = make_train_step(
        lambda p, b: llama.loss_fn(p, cfg, b), opt, donate=False
    )
    data = synthetic_lm_batches(4, 16, cfg.vocab_size)
    for _ in range(3):
        state, _ = step(state, next(data))

    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(state, wait=True)
    restored = ckpt.restore(jax.tree_util.tree_map(jnp.zeros_like, state))
    ckpt.close()
    assert int(restored.step) == 3


def test_trainer_profile_capture(tmp_path):
    """ProfileSpec window produces an XPlane trace dump."""
    import os

    from nexus_tpu.api.runtime_spec import (
        JaxXlaRuntime, ModelRef, ParallelismSpec, ProfileSpec, TpuSliceSpec,
        TrainSpec,
    )
    from nexus_tpu.runtime.entrypoints import run_template_runtime

    prof_dir = str(tmp_path / "trace")
    rt = JaxXlaRuntime(
        mode="train",
        model=ModelRef(family="mlp", preset="tiny"),
        tpu=TpuSliceSpec(accelerator="v5e", topology="1x1", slice_count=1),
        parallelism=ParallelismSpec(),
        train=TrainSpec(batch_size=8, steps=8, learning_rate=1e-2),
        profile=ProfileSpec(enabled=True, directory=prof_dir, start_step=1,
                            num_steps=2),
    )
    metrics = run_template_runtime(rt)
    assert metrics["profile_dir"] == prof_dir
    dumped = []
    for root, _, files in os.walk(prof_dir):
        dumped += [f for f in files if f.endswith(".xplane.pb")]
    assert dumped, f"no xplane trace written under {prof_dir}"


def test_runtime_spec_profile_roundtrip():
    from nexus_tpu.api.runtime_spec import JaxXlaRuntime, ProfileSpec

    rt = JaxXlaRuntime(profile=ProfileSpec(enabled=True, directory="/x",
                                           start_step=5, num_steps=7))
    rt2 = JaxXlaRuntime.from_dict(rt.to_dict())
    assert rt2.profile == rt.profile


def test_preemption_checkpoints_and_resumes(tmp_path):
    """SIGTERM-style cancellation mid-run saves a checkpoint; the rerun
    resumes from it (the slice-preemption elasticity path)."""
    import threading

    from nexus_tpu.api.runtime_spec import (
        CheckpointSpec, JaxXlaRuntime, ModelRef, ParallelismSpec,
        TpuSliceSpec, TrainSpec,
    )
    from nexus_tpu.runtime.entrypoints import run_template_runtime
    from nexus_tpu.utils.signals import CancelToken

    ckpt_dir = str(tmp_path / "ckpt")
    base = dict(
        mode="train",
        model=ModelRef(family="mlp", preset="tiny"),
        tpu=TpuSliceSpec(accelerator="v5e", topology="1x1", slice_count=1),
        parallelism=ParallelismSpec(),
        checkpoint=CheckpointSpec(enabled=True, directory=ckpt_dir,
                                  interval_steps=1000, resume=True),
    )
    rt = JaxXlaRuntime(
        train=TrainSpec(batch_size=8, steps=10**6, learning_rate=1e-2), **base
    )

    cancel = CancelToken()
    results = {}

    def run():
        results["m"] = run_template_runtime(rt, cancel=cancel)

    t = threading.Thread(target=run)
    t.start()
    import time

    time.sleep(6)  # let a few steps run (includes compile)
    cancel.cancel()
    t.join(timeout=120)
    assert not t.is_alive()
    m = results["m"]
    assert m["interrupted"] is True
    assert m["steps"] < 10**6

    # rerun without cancellation: resumes from the preemption checkpoint
    rt2 = JaxXlaRuntime(
        train=TrainSpec(batch_size=8, steps=m["steps"] + 3,
                        learning_rate=1e-2),
        **base,
    )
    m2 = run_template_runtime(rt2)
    assert m2["resumed_from_step"] >= 1
    assert m2["interrupted"] is False
