"""In-process end-to-end: real run loop, informers, watch events, workers —
two fake clusters (reference Tier 2 analogue: Test_ControllerMain,
controller_test.go:1287-1336, which asserts create→visible-on-shard and
update→propagated within ~1s)."""

import time

import pytest

from nexus_tpu.api.template import NexusAlgorithmTemplate
from nexus_tpu.api.types import Secret
from nexus_tpu.cluster.store import ClusterStore, NotFoundError
from nexus_tpu.controller.controller import Controller
from nexus_tpu.shards.shard import Shard
from nexus_tpu.utils.telemetry import StatsdClient
from tests.test_controller_sync import NS, make_secret, make_template

WAIT = 5.0


def wait_for(predicate, timeout=WAIT, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if predicate():
                return True
        except NotFoundError:
            pass
        time.sleep(interval)
    return False


@pytest.fixture
def running_controller():
    controller_store = ClusterStore("controller")
    shard_store = ClusterStore("shard0")
    shard = Shard("e2e-alias", "shard0", shard_store)
    controller = Controller(
        controller_store, [shard], statsd=StatsdClient("test"), resync_period=0.5
    )
    controller.run(workers=2)
    yield controller, controller_store, shard_store
    controller.stop()


def test_full_loop_create_update_delete(running_controller):
    controller, controller_store, shard_store = running_controller

    # CREATE: template + dependent secret land on the shard
    controller_store.create(make_secret("secret-1", {"k": "v1"}))
    controller_store.create(make_template(secrets=["secret-1"]))

    assert wait_for(
        lambda: shard_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1") is not None
    ), "template never appeared on shard"
    assert wait_for(
        lambda: shard_store.get(Secret.KIND, NS, "secret-1").data == {"k": "v1"}
    ), "secret never appeared on shard"

    # controller status converges to Ready
    assert wait_for(
        lambda: (
            controller_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
            .status.conditions[0].status
            == "True"
        )
    )

    # UPDATE: spec mutation propagates (the reference's versionTag flip)
    tmpl = controller_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
    tmpl.spec.container.version_tag = "v2.0.0"
    controller_store.update(tmpl)
    assert wait_for(
        lambda: (
            shard_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
            .spec.container.version_tag
            == "v2.0.0"
        )
    ), "spec update never propagated"

    # secret data drift propagates
    sec = controller_store.get(Secret.KIND, NS, "secret-1")
    sec.data = {"k": "v2"}
    controller_store.update(sec)
    assert wait_for(
        lambda: shard_store.get(Secret.KIND, NS, "secret-1").data == {"k": "v2"}
    ), "secret update never propagated"

    # DELETE: fan-out removes the template (and GC takes the secret) on shard
    controller_store.delete(NexusAlgorithmTemplate.KIND, NS, "algo-1")

    def gone():
        try:
            shard_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
            return False
        except NotFoundError:
            return True

    assert wait_for(gone), "template never deleted from shard"


def test_shard_drift_repaired_by_resync(running_controller):
    """Level-triggered repair: out-of-band shard tampering is reverted by the
    periodic resync without any controller-cluster event."""
    controller, controller_store, shard_store = running_controller
    controller_store.create(make_template())
    assert wait_for(
        lambda: shard_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1") is not None
    )

    tampered = shard_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
    tampered.spec.container.version_tag = "tampered"
    shard_store.update(tampered)

    assert wait_for(
        lambda: (
            shard_store.get(NexusAlgorithmTemplate.KIND, NS, "algo-1")
            .spec.container.version_tag
            == "v1.0.0"
        ),
        timeout=10.0,
    ), "resync never repaired shard drift"


def test_bulk_convergence_at_volume():
    """Scale tier: 150 templates (sharing 3 secrets) across TWO shards
    converge well inside the reference's operational envelope — the
    default token bucket is 50 items/s with burst 300 (reference
    .helm/values.yaml:165-169), so the initial flood fits the burst and
    the whole fleet must be synced in seconds, not minutes."""
    controller_store = ClusterStore("controller")
    shard_stores = [ClusterStore("shard0"), ClusterStore("shard1")]
    shards = [
        Shard("bulk", f"shard{i}", s) for i, s in enumerate(shard_stores)
    ]
    controller = Controller(
        controller_store, shards, statsd=StatsdClient("bulk"),
        resync_period=5.0,
    )
    n = 150
    secrets = [f"bulk-s{i}" for i in range(3)]
    for s in secrets:
        controller_store.create(make_secret(s, {"k": "v"}))
    controller.run(workers=4)
    try:
        for i in range(n):
            controller_store.create(
                make_template(f"bulk-{i}", secrets=[secrets[i % 3]])
            )

        def all_synced():
            for store in shard_stores:
                if len(store.list(NexusAlgorithmTemplate.KIND, NS)) < n:
                    return False
            return True

        assert wait_for(all_synced, timeout=45), (
            f"only {[len(s.list(NexusAlgorithmTemplate.KIND, NS)) for s in shard_stores]}"
            f"/{n} synced"
        )
        # every template Ready=True on the controller side
        def all_ready():
            for i in range(n):
                tmpl = controller_store.get(
                    NexusAlgorithmTemplate.KIND, NS, f"bulk-{i}"
                )
                conds = tmpl.status.conditions
                if not conds or conds[0].status != "True":
                    return False
            return True

        assert wait_for(all_ready, timeout=30), "not all templates Ready"
    finally:
        controller.stop()
