"""Bench outage hardening (VERDICT r4 item 2): a wedged TPU tunnel must
never zero a round again.

Round 4's driver artifact was a failure record — the bench spent its whole
1500 s deadline at 'initializing backend' and reported nothing. These tests
pin the round-5 fix: a backend-init probe under a short sub-deadline
fast-fails with a ``last_known_good`` carrying EVERY previously measured
axis, and the hermetic control-plane p50 stage measures with no TPU at all.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FULL_CACHE = {
    # shape of a completed round's full-keyed result (every axis)
    "metric": "llama_train_mfu",
    "value": 0.62,
    "unit": "mfu_fraction",
    "vs_baseline": 1.77,
    "preset": "400m",
    "seq_len": 2048,
    "mfu_1b": 0.58,
    "decode_tokens_per_sec": 190.0,
    "decode_tokens_per_sec_int8_kv": 180.0,
    "serve_tokens_per_sec": 400.0,
    "serve_vs_batch1_decode": 2.1,
    "serve16_tokens_per_sec": 520.0,
    "serve16_vs_batch1_decode": 2.7,
    "decode_tokens_per_sec_speculative": 210.0,
    "speculative_acceptance_rate": 0.55,
    "template_to_running_p50_s": 0.05,
    "measured_at": "2026-07-31T00:00:00+00:00",
}


def test_backend_init_hang_fast_fails_with_full_keyed_lkg(tmp_path):
    """A simulated backend-init hang (probe command that sleeps forever)
    produces a full-keyed result well inside the bench deadline: rc=1,
    value 0.0 + error (nothing was measured), and last_known_good riding
    ALL cached axes — not just the train headline."""
    cache_path = tmp_path / "bench_cache.json"
    cache_path.write_text(json.dumps(FULL_CACHE))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # bench must think a TPU is expected
    env.update(
        NEXUS_BENCH_INIT_PROBE_CMD="sleep 600",
        NEXUS_BENCH_INIT_PROBE_S="2",
        NEXUS_BENCH_CACHE=str(cache_path),
        NEXUS_BENCH_CONTROL_PLANE="0",  # keep the test fast
        NEXUS_BENCH_SWEEP_LOG="off",
        NEXUS_BENCH_DEADLINE_S="150",
    )
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        timeout=140, env=env, cwd=REPO,
    )
    wall = time.monotonic() - t0
    assert proc.returncode == 1, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["value"] == 0.0
    assert "error" in out and "probe" in out["error"]
    lkg = out["last_known_good"]
    for key in (
        "value", "mfu_1b", "decode_tokens_per_sec", "serve_tokens_per_sec",
        "serve_vs_batch1_decode", "serve16_tokens_per_sec",
        "decode_tokens_per_sec_speculative",
        "speculative_acceptance_rate", "template_to_running_p50_s",
    ):
        assert key in lkg, (key, lkg)
    # fast-fail means seconds of probe sub-deadline + interpreter/jax
    # import overhead — nowhere near the 1500 s round-4 burn
    assert wall < 90, wall


def test_persistent_compilation_cache_policy(tmp_path, monkeypatch):
    """The shared compile-cache helper: explicit dir always configures;
    'off' disables; the repo default engages only on a resolved TPU
    backend (tests run on CPU, so repo_default must no-op here and never
    create the shared .jax_cache)."""
    import jax
    import pytest

    from nexus_tpu.utils import hw

    if hw.is_tpu():  # pragma: no cover — conftest forces CPU
        pytest.skip("CPU-branch assertions; repo default engages on TPU")
    monkeypatch.delenv("NEXUS_XLA_CACHE_DIR", raising=False)
    explicit = str(tmp_path / "xla_cache")
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        assert hw.enable_persistent_compilation_cache(explicit) == explicit
        assert os.path.isdir(explicit)
        assert jax.config.jax_compilation_cache_dir == explicit

        monkeypatch.setenv("NEXUS_XLA_CACHE_DIR", "off")
        assert hw.enable_persistent_compilation_cache() is None
        monkeypatch.delenv("NEXUS_XLA_CACHE_DIR", raising=False)

        # CPU backend: the repo default must not engage (config stays
        # what the finally-block below will clear, not the repo dir)
        jax.config.update("jax_compilation_cache_dir", None)
        assert hw.enable_persistent_compilation_cache(
            repo_default=True
        ) is None
        assert jax.config.jax_compilation_cache_dir is None
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min
        )


def test_runtime_package_lazy_exports():
    """The runtime package's PEP 562 lazy exports resolve to the real
    objects (the eager imports were dropped to keep orbax/JAX out of the
    controller's first reconcile — the API surface must not regress)."""
    import nexus_tpu.runtime as rt

    from nexus_tpu.runtime.entrypoints import run_template_runtime
    from nexus_tpu.runtime.launcher import LocalLauncher
    from nexus_tpu.runtime.materializer import materialize_job

    assert rt.run_template_runtime is run_template_runtime
    assert rt.LocalLauncher is LocalLauncher
    assert rt.materialize_job is materialize_job
    try:
        rt.not_an_export
    except AttributeError:
        pass
    else:  # pragma: no cover
        raise AssertionError("unknown attribute must raise AttributeError")


def test_backend_probe_mismatched_cache_not_reported(tmp_path):
    """A cached result from a DIFFERENT bench configuration must not ride
    along as last_known_good — a stale fallback has to be the same
    measurement."""
    cache_path = tmp_path / "bench_cache.json"
    cache_path.write_text(json.dumps({**FULL_CACHE, "preset": "1b"}))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(
        NEXUS_BENCH_INIT_PROBE_CMD="sleep 600",
        NEXUS_BENCH_INIT_PROBE_S="2",
        NEXUS_BENCH_CACHE=str(cache_path),
        NEXUS_BENCH_CONTROL_PLANE="0",
        NEXUS_BENCH_SWEEP_LOG="off",
        NEXUS_BENCH_DEADLINE_S="150",
    )
    proc = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        timeout=140, env=env, cwd=REPO,
    )
    assert proc.returncode == 1
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "last_known_good" not in out


def test_control_plane_bench_hermetic(tmp_path):
    """The control-plane p50 tool measures template-to-running through the
    real controller + workload plane, CPU-only, in seconds."""
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "bench_control_plane.py"),
         "--templates", "4", "--timeout", "60"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "template_to_running_p50_s"
    assert rec["n_samples"] == 4
    assert 0 < rec["value"] < 30
    # the controller's own rolling-p50 gauge is the published number
    assert rec["controller_p50_gauge"] is not None
