"""Serve-plane observability (PR 12, nexus_tpu/obs/).

Load-bearing properties:

  * the TRACE SCHEMA is frozen: span kinds, field names, and field
    ORDER of a real traced serve run match the golden file
    (tests/golden/serve_trace_schema.json) — downstream tooling
    (trace_summary, the obs smoke validator, future routers) parses by
    position and name;
  * tracing is PURE OBSERVATION: a traced engine's tokens are
    byte-identical to an untraced engine's on the same queue;
  * the flight recorder is bounded, trips exactly once per reason per
    run, and a drain trip's tail events name the drained requests;
  * live gauges land in the in-process registry at wave boundaries
    with the SAME nearest-rank estimator the end-of-run rollup uses.
"""

import json
import math
import os

import numpy as np

from nexus_tpu.obs import (
    SPAN_FIELDS,
    FlightRecorder,
    LiveGauges,
    RollingPercentiles,
    ServeTracer,
    registry_snapshot,
    render_prometheus,
    validate_flight_dump,
    validate_trace,
)
from nexus_tpu.obs.recorder import FLIGHT_EVENT_KINDS
from nexus_tpu.runtime.serving import ServeRequest, ServingEngine
from nexus_tpu.utils.signals import CancelToken
from nexus_tpu.utils.telemetry import StatsdClient, percentile_nearest_rank
from tests.test_serving import _cyclic_model

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "serve_trace_schema.json")


def _traced_run(v=11, n_requests=6, **engine_kw):
    cfg, fwd = _cyclic_model(v, -1)
    tracer = ServeTracer()
    kw = dict(batch_size=2, max_len=128, chunk=4, kv_block_size=8)
    kw.update(engine_kw)
    engine = ServingEngine(fwd, {}, cfg, tracer=tracer, **kw)
    # shared preamble (one full block) → radix hits show up in spans
    reqs = [
        ServeRequest(prompt=[0, 1, 2, 3, 4, 5, 6, 7, (i % 5) + 1],
                     max_new_tokens=10)
        for i in range(n_requests)
    ]
    results, metrics = engine.serve(reqs)
    return tracer, results, metrics, engine


# ------------------------------------------------------- trace schema golden

def test_trace_schema_matches_golden_file():
    """The schema TABLE and a real run's observed spans both match the
    golden file — field names AND order. A schema change must be a
    deliberate golden-file update, never a drive-by."""
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert golden["span_fields"] == {
        k: ["kind"] + list(v) for k, v in SPAN_FIELDS.items()
    }
    assert golden["flight_event_kinds"] == list(FLIGHT_EVENT_KINDS)
    tracer, _results, _m, _eng = _traced_run()
    dump = tracer.to_dict()
    assert dump["schema_version"] == golden["trace_schema_version"]
    seen = set()
    for entry in dump["spans"]:
        for span in entry["timeline"]:
            kind = span["kind"]
            seen.add(kind)
            assert list(span.keys()) == golden["span_fields"][kind], kind
    # the mini-run exercises the core kinds (spec/drain kinds have their
    # own tiers below)
    assert {"enqueued", "admitted", "prefill_chunk", "first_token",
            "decode_wave", "lease_grow", "terminal"} <= seen


def test_trace_validates_and_timelines_are_complete():
    tracer, results, metrics, _eng = _traced_run()
    dump = tracer.to_dict()
    assert validate_trace(dump) == []
    assert metrics["traced"] is True
    for entry in dump["spans"]:
        tl = entry["timeline"]
        assert tl[0]["kind"] == "enqueued"
        assert tl[-1]["kind"] == "terminal"
        assert tl[-1]["status"] == "ok"
        # span t never decreases within one request's timeline
        ts = [s["t"] for s in tl]
        assert ts == sorted(ts)
        # committed tokens in spans reconcile with the result
        decoded = sum(s["tokens"] for s in tl
                      if s["kind"] == "decode_wave")
        assert decoded == results[entry["request"]].new_tokens


def test_trace_attributes_radix_hits_and_lease_growth():
    """Followers of a shared preamble carry matched_tokens/shared_blocks
    in their admitted span — the per-request cache attribution the
    disaggregation costing needs."""
    tracer, _results, metrics, _eng = _traced_run()
    dump = tracer.to_dict()
    admitted = [s for e in dump["spans"] for s in e["timeline"]
                if s["kind"] == "admitted"]
    assert sum(s["matched_tokens"] for s in admitted) == \
        metrics["prefix_hit_tokens"]
    hits = [s for s in admitted if s["matched_tokens"] > 0]
    assert hits and all(s["shared_blocks"] > 0 for s in hits)
    grows = [s for e in dump["spans"] for s in e["timeline"]
             if s["kind"] == "lease_grow"]
    assert grows and all(s["blocks_mapped"] >= 1 for s in grows)


def test_tracing_never_perturbs_tokens():
    """Pure observation: traced and untraced engines commit identical
    tokens on the same queue."""
    v = 11
    cfg, fwd = _cyclic_model(v, -1)
    reqs = [ServeRequest(prompt=[0, (i % 5) + 1], max_new_tokens=9)
            for i in range(5)]
    plain = ServingEngine(fwd, {}, cfg, batch_size=2, max_len=96,
                          chunk=4, kv_block_size=8,
                          flight_recorder=False, live_gauges=False)
    traced = ServingEngine(fwd, {}, cfg, batch_size=2, max_len=96,
                           chunk=4, kv_block_size=8,
                           tracer=ServeTracer())
    res_p, _ = plain.serve(reqs)
    res_t, _ = traced.serve(reqs)
    for a, b in zip(res_p, res_t):
        np.testing.assert_array_equal(np.array(a.tokens),
                                      np.array(b.tokens))


def test_trace_covers_speculative_attribution():
    """The prompt-lookup tier's decode spans split accepted vs rejected
    proposal tokens (rejected > 0 happens on cyclic text rarely; the
    accounting must at least reconcile with the engine ledger)."""
    cfg, fwd = _cyclic_model(9, -1)
    tracer = ServeTracer()
    engine = ServingEngine(fwd, {}, cfg, batch_size=2, max_len=128,
                           chunk=4, kv_block_size=8, lookup_ngram=2,
                           num_speculative=3, tracer=tracer)
    reqs = [ServeRequest(prompt=[0, 1, 2], max_new_tokens=12)
            for _ in range(3)]
    _results, metrics = engine.serve(reqs)
    dump = tracer.to_dict()
    assert validate_trace(dump) == []
    waves = [s for e in dump["spans"] for s in e["timeline"]
             if s["kind"] == "decode_wave"]
    assert waves
    # every span's accepted <= tokens committed that wave is NOT a
    # schema fact (a round commits accepted+1) — but totals reconcile:
    assert sum(s["tokens"] for s in waves) == metrics["committed_tokens"]


def test_validate_trace_flags_schema_drift():
    t = ServeTracer()
    t.begin(1)
    t.event(0, "enqueued", t=0.0, prompt_tokens=2, max_new_tokens=4)
    t.event(0, "terminal", t=1.0, status="ok", new_tokens=4,
            latency_s=1.0, finished_by_stop=False)
    import copy

    base = t.to_dict()
    assert validate_trace(base) == []
    # field injection (order break) is caught
    dump = copy.deepcopy(base)
    dump["spans"][0]["timeline"][0]["extra"] = 1
    assert any("fields" in p for p in validate_trace(dump))
    # unknown kind is caught
    dump2 = copy.deepcopy(base)
    dump2["spans"][0]["timeline"][1]["kind"] = "mystery"
    assert any("unknown kind" in p for p in validate_trace(dump2))
    # time travel is caught
    dump3 = copy.deepcopy(base)
    dump3["spans"][0]["timeline"][1]["t"] = -5.0
    assert any("backwards" in p for p in validate_trace(dump3))


# ------------------------------------------------------------ flight recorder

def test_flight_recorder_ring_is_bounded_and_trips():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("wave", t=float(i), wave=i)
    assert rec.events_recorded == 10
    dump = rec.trip("drain", t=10.0, detail={"drained": [1, 2]})
    assert validate_flight_dump(dump) == []
    assert len(dump["events"]) == 4  # capacity, not history
    assert [e["wave"] for e in dump["events"]] == [6, 7, 8, 9]
    assert rec.last_dump is dump and list(rec.dumps) == [dump]
    # the dump list is itself bounded (newest kept): sustained overload
    # tripping once per serve() run must not grow RSS
    small = FlightRecorder(capacity=2, max_dumps=3)
    for i in range(5):
        small.record("wave", t=float(i), wave=i)
        small.trip("drain", t=float(i), detail={"n": i})
    assert len(small.dumps) == 3
    assert [d["detail"]["n"] for d in small.dumps] == [2, 3, 4]
    assert small.last_dump["detail"]["n"] == 4


def test_engine_drain_trips_flight_recorder_with_drained_tail():
    """Kill-mid-serve: the dump's reason is 'drain', its detail and its
    tail drain_request events both name exactly the drained cohort."""
    cfg, fwd = _cyclic_model(11, -1)
    engine = ServingEngine(fwd, {}, cfg, batch_size=1, max_len=128,
                           chunk=4, kv_block_size=8)
    cancel = CancelToken()
    beats = [0]

    def hb(_c):
        beats[0] += 1
        if beats[0] >= 2:
            cancel.cancel(hard=True)

    reqs = [ServeRequest(prompt=[0, i + 1], max_new_tokens=40)
            for i in range(3)]
    _res, metrics = engine.serve(reqs, cancel=cancel, heartbeat=hb)
    assert metrics["interrupted"] is True
    dump = engine.last_flight_dump
    assert dump is not None and dump["reason"] == "drain"
    assert validate_flight_dump(dump) == []
    drained_ids = sorted(d.request_idx for d in engine.last_drain)
    assert sorted(dump["detail"]["drained"]) == drained_ids
    tail = [e for e in dump["events"] if e["kind"] == "drain_request"]
    assert sorted(e["request"] for e in tail) == drained_ids
    # the in-flight row's committed count survives into the dump
    admitted = [e for e in tail if e["admitted"]]
    assert admitted and all(e["committed"] > 0 for e in admitted)


def test_shed_storm_trips_flight_recorder_once():
    """An arrival burst past the bounded queue sheds >= storm_threshold
    requests at one boundary → exactly ONE storm dump."""
    cfg, fwd = _cyclic_model(9, -1)
    engine = ServingEngine(fwd, {}, cfg, batch_size=1, max_len=64,
                           chunk=4, max_queue_depth=1,
                           storm_threshold=3)
    reqs = [ServeRequest(prompt=[0, 1], max_new_tokens=4)
            for _ in range(8)]
    _res, metrics = engine.serve(reqs)
    assert metrics["shed_requests"] >= 3
    dump = engine.last_flight_dump
    assert dump is not None and dump["reason"] == "shed_storm"
    assert dump["detail"]["shed"] >= 3
    assert metrics["flight_dumps"] == 1
    sheds = [e for e in dump["events"] if e["kind"] == "shed"]
    assert len(sheds) >= 3


def test_flight_recorder_off_switch():
    cfg, fwd = _cyclic_model(9, -1)
    engine = ServingEngine(fwd, {}, cfg, batch_size=1, max_len=64,
                           chunk=4, flight_recorder=False)
    reqs = [ServeRequest(prompt=[0, 1], max_new_tokens=4)]
    _res, metrics = engine.serve(reqs)
    assert engine.flight_recorder is None
    assert metrics["flight_recorder_events"] == 0


# ---------------------------------------------------------------- live gauges

def test_rolling_percentiles_window_and_estimator():
    rp = RollingPercentiles(window=4)
    assert math.isnan(rp.percentile(0.95))  # empty window: NaN, never 0
    for x in (5.0, 1.0, 3.0):
        rp.add(x)
    assert rp.percentile(0.50) == percentile_nearest_rank(
        [5.0, 1.0, 3.0], 0.50
    )
    for x in (10.0, 20.0, 30.0, 40.0):
        rp.add(x)  # evicts the first three
    assert len(rp) == 4 and rp.count == 7
    assert rp.percentile(0.0) == 10.0
    # the publish path's sort-once variant agrees rank for rank
    assert rp.percentiles((0.0, 0.50, 0.95)) == [
        rp.percentile(0.0), rp.percentile(0.50), rp.percentile(0.95),
    ]
    assert all(math.isnan(v)
               for v in RollingPercentiles().percentiles((0.5, 0.95)))


def test_engine_publishes_wave_gauges_into_registry():
    client = StatsdClient("t-obs")
    cfg, fwd = _cyclic_model(11, -1)
    gauges = LiveGauges(client=client, tags=["engine:t0"])
    engine = ServingEngine(fwd, {}, cfg, batch_size=2, max_len=96,
                           chunk=4, kv_block_size=8)
    # the engine publishes through the PROCESS-default client (one
    # registry per process is the point), so assert its cadence via
    # the metrics ledger...
    reqs = [ServeRequest(prompt=[0, i + 1], max_new_tokens=6)
            for i in range(4)]
    _res, metrics = engine.serve(reqs)
    assert metrics["live_gauge_publishes"] == metrics["decode_chunks"]
    # ...and prove the publication surface itself against a hermetic
    # client, gauge by gauge:
    gauges.observe_finish(0.25, 0.1)
    gauges.publish(queue_depth=3, running_rows=2, free_pool_blocks=7,
                   host_cache_bytes=0, committed_tokens=42, waves=5)
    snap = client.snapshot()
    g = snap["gauges"]
    assert g["t-obs.serve_queue_depth"] == 3
    assert g["t-obs.serve_running_rows"] == 2
    assert g["t-obs.serve_free_pool_blocks"] == 7
    assert g["t-obs.serve_committed_tokens"] == 42
    assert g["t-obs.serve_ttft_p95_s"] == 0.25
    assert g["t-obs.serve_queue_p50_s"] == 0.1
    assert (("t-obs.serve_queue_depth", ("engine:t0",))
            in snap["series"])


def test_empty_percentile_windows_publish_no_gauge():
    client = StatsdClient("t-obs-empty")
    gauges = LiveGauges(client=client)
    gauges.publish(queue_depth=0, running_rows=0, free_pool_blocks=0,
                   host_cache_bytes=0, committed_tokens=0, waves=0)
    assert "t-obs-empty.serve_ttft_p95_s" not in client.snapshot()["gauges"]


# ------------------------------------------------------------------ exposition

def test_prometheus_render_and_snapshot_roundtrip():
    client = StatsdClient("app-x")
    client.gauge("serve_queue_depth", 4, tags=["engine:a"])
    client.gauge("serve_queue_depth", 7, tags=["engine:b"])
    client.gauge("reconcile.latency", 0.5)
    text = render_prometheus(client)
    assert "# TYPE app_x_serve_queue_depth gauge" in text
    assert 'app_x_serve_queue_depth{engine="a"} 4' in text
    assert 'app_x_serve_queue_depth{engine="b"} 7' in text
    assert "app_x_reconcile_latency 0.5" in text
    # deterministic: two renders of one state are byte-identical
    assert text == render_prometheus(client)
    snap = registry_snapshot(client)
    assert {"name": "app-x.serve_queue_depth", "tags": ["engine:a"],
            "value": 4} in snap["series"]
    json.dumps(snap)  # JSON-safe by construction
