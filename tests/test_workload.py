"""Workload plane: the controller materializes a synced template's jax_xla
runtime as Jobs + headless Services on the shard, watches Job status, and
back-propagates workload phase into template status (VERDICT r1 item 2; the
north star's "template fan-out launches JAX/XLA jobs on the shard").
"""

from nexus_tpu.api.runtime_spec import (
    JaxXlaRuntime,
    ModelRef,
    ParallelismSpec,
    TpuSliceSpec,
    TrainSpec,
)
from nexus_tpu.api.template import NexusAlgorithmTemplate
from nexus_tpu.api.types import Condition, LABEL_CONTROLLER_APP
from nexus_tpu.api.workload import Job, Service, aggregate_phase
from nexus_tpu.cluster.store import NotFoundError
from nexus_tpu.utils.telemetry import (
    METRIC_TEMPLATE_TO_RUNNING,
    METRIC_TEMPLATE_TO_RUNNING_P50,
)
from tests.test_controller_sync import NS, Fixture, make_template

import pytest


def runtime_block(slice_count=2):
    return JaxXlaRuntime(
        mode="train",
        model=ModelRef(family="llama", preset="tiny"),
        tpu=TpuSliceSpec(accelerator="v5e", topology="2x2", slice_count=slice_count),
        parallelism=ParallelismSpec(data=2 * slice_count, tensor=2),
        train=TrainSpec(batch_size=8, seq_len=32, steps=2),
    )


def make_runtime_template(name="tpu-algo", slice_count=2):
    tmpl = make_template(name)
    tmpl.spec.runtime = runtime_block(slice_count)
    return tmpl


def set_job_status(store, name, *, active=0, succeeded=0, failed=0,
                   condition=None, start_time=None):
    job = store.get(Job.KIND, NS, name)
    job.status.active = active
    job.status.ready = active
    job.status.succeeded = succeeded
    job.status.failed = failed
    job.status.start_time = start_time
    job.status.conditions = (
        [Condition(type=condition, status="True")] if condition else []
    )
    store.update_status(job)


def test_workload_jobs_and_services_applied():
    f = Fixture()
    f.seed_controller(make_runtime_template())

    f.controller.template_sync_handler(NS, "tpu-algo")

    for slice_name in ("tpu-algo-s0", "tpu-algo-s1"):
        job = f.shard_store.get(Job.KIND, NS, slice_name)
        svc = f.shard_store.get(Service.KIND, NS, slice_name)
        # provenance + ownership: owned by the SHARD-side template copy
        shard_tmpl = f.shard_store.get(NexusAlgorithmTemplate.KIND, NS, "tpu-algo")
        assert job.metadata.labels[LABEL_CONTROLLER_APP]
        assert job.metadata.owner_references[0].uid == shard_tmpl.metadata.uid
        assert svc.metadata.owner_references[0].uid == shard_tmpl.metadata.uid
        # TPU scheduling materialized
        pod = job.spec["template"]["spec"]
        assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x2"
        assert svc.spec["clusterIP"] == "None"

    status = f.controller_store.get(
        NexusAlgorithmTemplate.KIND, NS, "tpu-algo"
    ).status
    assert status.workload_phases == {"shard0": "Pending"}
    assert status.workload_phase == "Pending"


def test_workload_phase_running_emits_t2r_gauges_once():
    f = Fixture()
    f.seed_controller(make_runtime_template())
    f.controller.template_sync_handler(NS, "tpu-algo")

    set_job_status(f.shard_store, "tpu-algo-s0", active=1)
    set_job_status(f.shard_store, "tpu-algo-s1", active=1)
    f.controller.template_sync_handler(NS, "tpu-algo")

    status = f.controller_store.get(
        NexusAlgorithmTemplate.KIND, NS, "tpu-algo"
    ).status
    assert status.workload_phase == "Running"

    statsd = f.controller.statsd
    t2r = [h for h in statsd.history if METRIC_TEMPLATE_TO_RUNNING in h[0]
           and "p50" not in h[0]]
    p50 = [h for h in statsd.history if METRIC_TEMPLATE_TO_RUNNING_P50 in h[0]]
    assert len(t2r) == 1 and len(p50) == 1
    assert t2r[0][1] >= 0.0

    # second reconcile at Running must NOT re-emit (first-transition metric)
    f.controller.template_sync_handler(NS, "tpu-algo")
    t2r = [h for h in statsd.history if METRIC_TEMPLATE_TO_RUNNING in h[0]
           and "p50" not in h[0]]
    assert len(t2r) == 1


def test_workload_cross_slice_failfast():
    """Multislice failure policy: one slice terminally Failed → sibling
    slice Jobs are stopped and not relaunched (VERDICT r1 missing #6)."""
    f = Fixture()
    f.seed_controller(make_runtime_template())
    f.controller.template_sync_handler(NS, "tpu-algo")

    set_job_status(f.shard_store, "tpu-algo-s0", failed=1, condition="Failed")
    set_job_status(f.shard_store, "tpu-algo-s1", active=1)
    f.controller.template_sync_handler(NS, "tpu-algo")

    # sibling stopped...
    with pytest.raises(NotFoundError):
        f.shard_store.get(Job.KIND, NS, "tpu-algo-s1")
    # ...and NOT relaunched by another reconcile while the failure is current
    f.controller.template_sync_handler(NS, "tpu-algo")
    with pytest.raises(NotFoundError):
        f.shard_store.get(Job.KIND, NS, "tpu-algo-s1")

    status = f.controller_store.get(
        NexusAlgorithmTemplate.KIND, NS, "tpu-algo"
    ).status
    assert status.workload_phase == "Failed"


def test_workload_spec_change_relaunches_after_failure():
    f = Fixture()
    f.seed_controller(make_runtime_template())
    f.controller.template_sync_handler(NS, "tpu-algo")
    set_job_status(f.shard_store, "tpu-algo-s0", failed=1, condition="Failed")
    f.controller.template_sync_handler(NS, "tpu-algo")

    # user pushes a new spec revision → different Job manifests
    tmpl = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "tpu-algo")
    tmpl.spec.runtime.train.steps = 7
    updated = f.controller_store.update(tmpl)
    f.controller.template_lister._set(updated)

    f.controller.template_sync_handler(NS, "tpu-algo")

    # failed job replaced by the fresh revision, all slices relaunched
    for slice_name in ("tpu-algo-s0", "tpu-algo-s1"):
        job = f.shard_store.get(Job.KIND, NS, slice_name)
        assert job.phase() == "Pending"
        assert '"steps":7' in _runtime_env(job)


def _runtime_env(job):
    env = job.spec["template"]["spec"]["containers"][0]["env"]
    return next(e["value"] for e in env if e["name"] == "NEXUS_RUNTIME_SPEC")


def test_workload_runtime_removal_cleans_up():
    """Dropping the runtime block stops the materialized Jobs/Services and
    clears workload status (instead of leaving them running/stale)."""
    f = Fixture()
    f.seed_controller(make_runtime_template())
    f.controller.template_sync_handler(NS, "tpu-algo")
    set_job_status(f.shard_store, "tpu-algo-s0", active=1)
    set_job_status(f.shard_store, "tpu-algo-s1", active=1)
    f.controller.template_sync_handler(NS, "tpu-algo")
    assert (
        f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "tpu-algo")
        .status.workload_phase
        == "Running"
    )

    tmpl = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "tpu-algo")
    tmpl.spec.runtime = None
    updated = f.controller_store.update(tmpl)
    f.controller.template_lister._set(updated)
    f.controller.template_sync_handler(NS, "tpu-algo")

    for slice_name in ("tpu-algo-s0", "tpu-algo-s1"):
        with pytest.raises(NotFoundError):
            f.shard_store.get(Job.KIND, NS, slice_name)
        with pytest.raises(NotFoundError):
            f.shard_store.get(Service.KIND, NS, slice_name)
    status = f.controller_store.get(
        NexusAlgorithmTemplate.KIND, NS, "tpu-algo"
    ).status
    assert status.workload_phase == "" and status.workload_phases == {}


def test_workload_slice_count_reduction_prunes_stale_slices():
    """slice_count 2 -> 1 must delete the no-longer-declared slice's Job and
    Service, and its phase must not linger in the aggregate."""
    f = Fixture()
    f.seed_controller(make_runtime_template(slice_count=2))
    f.controller.template_sync_handler(NS, "tpu-algo")
    assert f.shard_store.get(Job.KIND, NS, "tpu-algo-s1") is not None

    tmpl = f.controller_store.get(NexusAlgorithmTemplate.KIND, NS, "tpu-algo")
    tmpl.spec.runtime = runtime_block(slice_count=1)
    updated = f.controller_store.update(tmpl)
    f.controller.template_lister._set(updated)
    f.controller.template_sync_handler(NS, "tpu-algo")

    # single-slice naming: the job is now "tpu-algo" (no -sN suffix)
    assert f.shard_store.get(Job.KIND, NS, "tpu-algo") is not None
    for stale in ("tpu-algo-s0", "tpu-algo-s1"):
        with pytest.raises(NotFoundError):
            f.shard_store.get(Job.KIND, NS, stale)
        with pytest.raises(NotFoundError):
            f.shard_store.get(Service.KIND, NS, stale)


def test_t2r_emitted_when_running_window_missed():
    """A fast workload can go Pending -> Succeeded between reconciles; the
    t2r gauge must still fire, using the Jobs' recorded startTime."""
    from nexus_tpu.api.types import utcnow

    f = Fixture()
    f.seed_controller(make_runtime_template())
    f.controller.template_sync_handler(NS, "tpu-algo")

    started = utcnow().isoformat()
    for name in ("tpu-algo-s0", "tpu-algo-s1"):
        set_job_status(f.shard_store, name, succeeded=1, condition="Complete",
                       start_time=started)
    f.controller.template_sync_handler(NS, "tpu-algo")

    status = f.controller_store.get(
        NexusAlgorithmTemplate.KIND, NS, "tpu-algo"
    ).status
    assert status.workload_phase == "Succeeded"
    t2r = [h for h in f.controller.statsd.history
           if METRIC_TEMPLATE_TO_RUNNING in h[0] and "p50" not in h[0]]
    assert len(t2r) == 1


def test_aggregate_phase_ordering():
    assert aggregate_phase(["Running", "Pending"]) == "Pending"
    assert aggregate_phase(["Running", "Failed"]) == "Failed"
    assert aggregate_phase(["Succeeded", "Succeeded"]) == "Succeeded"
    assert aggregate_phase(["Running", "Running"]) == "Running"
    assert aggregate_phase([]) == ""
