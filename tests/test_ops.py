"""Op correctness: norms, rope, attention (xla + pallas-interpret + ring)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from nexus_tpu.ops.attention import attention_xla, flash_attention
from nexus_tpu.ops.norms import rms_norm
from nexus_tpu.ops.ring_attention import ring_attention
from nexus_tpu.ops.rope import apply_rope, rope_cos_sin
from nexus_tpu.parallel.mesh import MeshPlan, build_mesh


def test_rms_norm_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    w = jax.random.normal(jax.random.PRNGKey(1), (16,)) + 1.0
    got = rms_norm(x, w)
    expected = x / np.sqrt(np.mean(np.square(x), axis=-1, keepdims=True) + 1e-5) * w
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_rope_preserves_norm_and_shape():
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 8))
    cos, sin = rope_cos_sin(16, 8, theta=10000.0)
    out = apply_rope(q, cos, sin)
    assert out.shape == q.shape
    # rotation preserves per-pair norms
    def pair_norms(x):
        h = x.shape[-1] // 2
        return np.sqrt(x[..., :h] ** 2 + x[..., h:] ** 2)
    np.testing.assert_allclose(pair_norms(np.array(out)), pair_norms(np.array(q)),
                               rtol=1e-5, atol=1e-5)


def test_rope_position_offset_consistency():
    """Computing positions [4:8] via offset must equal slicing a full table."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 2, 8))
    cos_full, sin_full = rope_cos_sin(8, 8)
    cos_off, sin_off = rope_cos_sin(4, 8, position_offset=4)
    np.testing.assert_allclose(np.array(cos_full[4:]), np.array(cos_off), rtol=1e-6)
    out_a = apply_rope(q, cos_full[4:], sin_full[4:])
    out_b = apply_rope(q, cos_off, sin_off)
    np.testing.assert_allclose(np.array(out_a), np.array(out_b), rtol=1e-6)


def _naive_causal_attention(q, k, v):
    b, sq, h, d = q.shape
    n_rep = h // k.shape[2]
    k = np.repeat(np.array(k), n_rep, axis=2)
    v = np.repeat(np.array(v), n_rep, axis=2)
    out = np.zeros_like(np.array(q), dtype=np.float32)
    for bi in range(b):
        for hi in range(h):
            logits = (np.array(q)[bi, :, hi] @ k[bi, :, hi].T) / np.sqrt(d)
            mask = np.tril(np.ones((sq, sq), bool))
            logits = np.where(mask, logits, -1e30)
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            out[bi, :, hi] = p @ v[bi, :, hi]
    return out


def test_attention_xla_matches_naive():
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (2, 16, 4, 8))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 2, 8))  # GQA 2:1
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 2, 8))
    got = attention_xla(q, k, v, causal=True)
    np.testing.assert_allclose(np.array(got), _naive_causal_attention(q, k, v),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_matches_xla_interpret():
    """Pallas kernel correctness via interpret mode on CPU."""
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (2, 128, 4, 64))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 2, 64))
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 128, 2, 64))
    ref = attention_xla(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.array(got), np.array(ref), rtol=2e-3, atol=2e-3)


def test_flash_attention_non_causal():
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 128, 2, 64))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 128, 2, 64))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 128, 2, 64))
    ref = attention_xla(q, k, v, causal=False)
    got = flash_attention(q, k, v, causal=False, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.array(got), np.array(ref), rtol=2e-3, atol=2e-3)


def test_ring_attention_matches_full_attention():
    """Exact sequence-parallel attention over an 8-way ring == dense."""
    try:
        from jax import shard_map
        smap = functools.partial(shard_map)
    except ImportError:
        from jax.experimental.shard_map import shard_map as smap  # noqa

    mesh = build_mesh(MeshPlan(sequence=8))
    b, s, h, d = 2, 64, 4, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, 2, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, 2, d))

    ref = attention_xla(q, k, v, causal=True)

    seq_spec = P(None, "sequence", None, None)
    ring_fn = smap(
        functools.partial(ring_attention, axis_name="sequence", causal=True),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
    )
    got = jax.jit(ring_fn)(q, k, v)
    np.testing.assert_allclose(np.array(got), np.array(ref), rtol=2e-3, atol=2e-3)


def test_moe_routing_respects_capacity_and_combines():
    from nexus_tpu.ops.moe import default_capacity, moe_combine_dense, \
        moe_dispatch_dense, top_k_routing

    t, e, d, k = 32, 4, 8, 2
    cap = default_capacity(t, e, k)
    logits = jax.random.normal(jax.random.PRNGKey(0), (t, e))
    routing = top_k_routing(logits, k, cap)
    assert routing.dispatch.shape == (t, e, cap)
    # each expert slot holds at most one token
    per_slot = np.array(routing.dispatch).sum(axis=0)  # (e, cap)
    assert per_slot.max() <= 1.0 + 1e-6
    # each token dispatched to at most k slots
    per_token = np.array(routing.dispatch).sum(axis=(1, 2))
    assert per_token.max() <= k + 1e-6
    # combine weights per token sum to ≤ 1 (== 1 when nothing dropped)
    weights = np.array(routing.combine).sum(axis=(1, 2))
    assert weights.max() <= 1.0 + 1e-5
    assert routing.aux_loss.shape == ()

    # identity experts → output is a convex recombination of inputs
    x = jax.random.normal(jax.random.PRNGKey(1), (t, d))
    buffers = moe_dispatch_dense(x, routing)
    recombined = moe_combine_dense(buffers, routing)
    # tokens fully routed (weight 1) must round-trip exactly
    full = weights > 1.0 - 1e-5
    np.testing.assert_allclose(
        np.array(recombined)[full], np.array(x)[full], rtol=1e-4, atol=1e-5
    )


def test_flash_attention_backward_matches_xla():
    """Pallas flash backward (dq/dk/dv kernels) vs XLA autodiff reference."""
    from nexus_tpu.ops.attention import attention_xla, flash_attention

    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    b, s, h, d = 2, 256, 4, 64
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(attention_xla(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gx):
        np.testing.assert_allclose(np.array(a), np.array(b_), rtol=2e-3, atol=2e-3)


def test_flash_attention_backward_gqa():
    """GQA: kv-head grads sum over their broadcast query-head groups."""
    from nexus_tpu.ops.attention import attention_xla, flash_attention

    key = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(key, 3)
    b, s, hq, hkv, d = 1, 128, 4, 2, 64
    q = jax.random.normal(kq, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, hkv, d), jnp.float32)

    gf = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v) ** 2), argnums=(0, 1, 2)
    )(q, k, v)
    gx = jax.grad(
        lambda q, k, v: jnp.sum(attention_xla(q, k, v) ** 2), argnums=(0, 1, 2)
    )(q, k, v)
    assert gf[1].shape == (b, s, hkv, d)
    for a, b_ in zip(gf, gx):
        np.testing.assert_allclose(np.array(a), np.array(b_), rtol=2e-3, atol=2e-3)


def test_chunked_softmax_xent_matches_dense():
    """ops/losses.py: vocab-chunked CE is exact vs the dense path (value and
    gradients), including a non-dividing vocab (tail-chunk masking)."""
    import jax
    import jax.numpy as jnp

    from nexus_tpu.ops.losses import chunked_softmax_xent, dense_softmax_xent

    key = jax.random.PRNGKey(0)
    b, s, d, v = 2, 16, 32, 103  # v deliberately not a multiple of chunk
    hidden = jax.random.normal(key, (b, s, d), jnp.float32)
    lm_head = jax.random.normal(jax.random.PRNGKey(1), (d, v), jnp.float32)
    targets = jax.random.randint(jax.random.PRNGKey(2), (b, s), 0, v,
                                 dtype=jnp.int32)

    for chunk in (16, 64, 103, 4096):
        dense, (dh, dw) = jax.value_and_grad(dense_softmax_xent, argnums=(0, 1))(
            hidden, lm_head, targets
        )
        ck, (ch, cw) = jax.value_and_grad(
            lambda h, w, t: chunked_softmax_xent(h, w, t, chunk=chunk),
            argnums=(0, 1),
        )(hidden, lm_head, targets)
        assert abs(float(dense) - float(ck)) < 1e-5, (chunk, dense, ck)
        assert float(jnp.max(jnp.abs(dh - ch))) < 1e-5
        assert float(jnp.max(jnp.abs(dw - cw))) < 1e-5


def test_llama_loss_ce_chunk_parity():
    import jax
    import jax.numpy as jnp

    from nexus_tpu.models import llama

    cfg_dense = llama.config("tiny", dtype=jnp.float32)
    cfg_chunk = llama.config("tiny", dtype=jnp.float32, ce_chunk=96)
    params = llama.init(jax.random.PRNGKey(0), cfg_dense)
    toks = jax.random.randint(
        jax.random.PRNGKey(1), (2, 33), 0, cfg_dense.vocab_size, dtype=jnp.int32
    )
    l_dense, _ = llama.loss_fn(params, cfg_dense, {"tokens": toks})
    l_chunk, _ = llama.loss_fn(params, cfg_chunk, {"tokens": toks})
    assert abs(float(l_dense) - float(l_chunk)) < 1e-4


def test_flash_attention_q_offset_fwd_bwd():
    """Tile-skipping must stay exact with a nonzero q_offset (decode /
    sequence-shard positioning): compare fwd + grads vs the XLA path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nexus_tpu.ops.attention import attention_xla, flash_attention

    b, sq, sk, h, d = 1, 128, 256, 2, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, sq, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, sk, h, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, sk, h, d), jnp.float32)
    off = 128  # q rows sit in the second half of the kv window

    def loss_ref(q, k, v):
        return (attention_xla(q, k, v, q_offset=off) ** 2).sum()

    def loss_fl(q, k, v):
        return (
            flash_attention(q, k, v, q_offset=off, interpret=True, block_q=64,
                            block_k=64) ** 2
        ).sum()

    out_ref = attention_xla(q, k, v, q_offset=off)
    out_fl = flash_attention(q, k, v, q_offset=off, interpret=True,
                             block_q=64, block_k=64)
    np.testing.assert_allclose(np.array(out_fl), np.array(out_ref),
                               rtol=2e-4, atol=2e-4)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gf):
        np.testing.assert_allclose(np.array(b_), np.array(a),
                                   rtol=2e-3, atol=2e-3)


def test_flash_attention_lse_value_and_grad():
    """flash_attention_lse: the lse output matches the dense logsumexp and
    its cotangent flows correctly (the block-merge contract ring attention
    builds on)."""
    from nexus_tpu.ops.attention import flash_attention_lse, _repeat_kv

    key = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(key, 3)
    b, s, hq, hkv, d = 2, 128, 4, 2, 64
    q = jax.random.normal(kq, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, hkv, d), jnp.float32)

    def ref(q, k, v):
        kr, vr = _repeat_kv(k, hq // hkv), _repeat_kv(v, hq // hkv)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kr) * d ** -0.5
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(s)[None, :]
        logits = jnp.where(cols <= rows, logits, -jnp.inf)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)  # (B,H,Q)
        probs = jnp.exp(logits - lse[..., None])
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, vr)
        return out, lse.transpose(0, 2, 1)  # (B,Q,H)

    out_f, lse_f = flash_attention_lse(q, k, v, causal=True, block_q=64,
                                       block_k=64, interpret=True)
    out_r, lse_r = ref(q, k, v)
    np.testing.assert_allclose(np.array(out_f), np.array(out_r),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.array(lse_f), np.array(lse_r),
                               rtol=2e-4, atol=2e-4)

    # a loss that uses BOTH outputs — lse cotangent must reach q and k
    def loss_flash(q, k, v):
        o, l = flash_attention_lse(q, k, v, causal=True, block_q=64,
                                   block_k=64, interpret=True)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))

    def loss_ref(q, k, v):
        o, l = ref(q, k, v)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.array(a), np.array(b_),
                                   rtol=5e-3, atol=5e-3)


def test_ring_attention_flash_blocks_match_dense():
    """Ring attention with flash inner blocks (interpret mode) == dense
    attention, values AND gradients, over an 8-way sequence mesh."""
    from nexus_tpu.ops.ring_attention import ring_attention

    try:
        from jax import shard_map
        smap = functools.partial(shard_map)
    except ImportError:
        from jax.experimental.shard_map import shard_map as smap  # noqa

    mesh = build_mesh(MeshPlan(sequence=8))
    b, s, h, d = 2, 64, 4, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, 2, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, 2, d))

    seq_spec = P(None, "sequence", None, None)
    ring_fn = smap(
        functools.partial(
            ring_attention, axis_name="sequence", causal=True,
            block_impl="flash",
        ),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        # pallas-in-shard_map limitation, see ring_attention_sharded
        **({"check_vma": False} if hasattr(jax, "shard_map")
           else {"check_rep": False}),
    )

    got = jax.jit(ring_fn)(q, k, v)
    ref = attention_xla(q, k, v, causal=True)
    np.testing.assert_allclose(np.array(got), np.array(ref), rtol=2e-3, atol=2e-3)

    g_ring = jax.grad(
        lambda q, k, v: jnp.sum(ring_fn(q, k, v) ** 2), argnums=(0, 1, 2)
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(attention_xla(q, k, v) ** 2), argnums=(0, 1, 2)
    )(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.array(a), np.array(b_),
                                   rtol=5e-3, atol=5e-3)


def test_moe_scatter_dispatch_matches_dense():
    """Scatter/gather dispatch+combine == dense one-hot einsums, values and
    gradients (the O(T·k·D)-movement alternative to O(T²·D) MXU work)."""
    from nexus_tpu.ops.moe import (
        default_capacity, moe_combine_dense, moe_combine_scatter,
        moe_dispatch_dense, moe_dispatch_scatter, top_k_routing,
    )

    t, e, d, k = 64, 4, 16, 2
    cap = default_capacity(t, e, k)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (t, d))
    logits = jax.random.normal(jax.random.PRNGKey(1), (t, e))

    def through(dispatch, combine):
        def f(x, logits):
            routing = top_k_routing(logits, k, cap)
            buf = dispatch(x, routing)
            # a stand-in "expert computation" that is position-sensitive
            out = combine(buf * (1.0 + jnp.arange(cap)[None, :, None] * 0.01),
                          routing)
            return out
        return f

    dense = through(lambda x, r: moe_dispatch_dense(x, r),
                    moe_combine_dense)
    scat = through(lambda x, r: moe_dispatch_scatter(x, r, e, cap),
                   moe_combine_scatter)

    np.testing.assert_allclose(np.array(dense(x, logits)),
                               np.array(scat(x, logits)),
                               rtol=1e-5, atol=1e-5)

    gd = jax.grad(lambda x, l: jnp.sum(dense(x, l) ** 2), argnums=(0, 1))(x, logits)
    gs = jax.grad(lambda x, l: jnp.sum(scat(x, l) ** 2), argnums=(0, 1))(x, logits)
    for a, b_ in zip(gd, gs):
        np.testing.assert_allclose(np.array(a), np.array(b_),
                                   rtol=1e-4, atol=1e-5)


def test_mixtral_scatter_dispatch_end_to_end():
    """dispatch_impl='scatter' trains and matches the einsum path's loss."""
    from nexus_tpu.models import mixtral

    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 256,
                              dtype=jnp.int32)
    cfg_e = mixtral.config("tiny", dtype=jnp.float32)
    cfg_s = mixtral.config("tiny", dtype=jnp.float32, dispatch_impl="scatter")
    params = mixtral.init(jax.random.PRNGKey(0), cfg_e)
    le, _ = mixtral.loss_fn(params, cfg_e, {"tokens": toks})
    ls, _ = mixtral.loss_fn(params, cfg_s, {"tokens": toks})
    assert abs(float(le) - float(ls)) < 1e-5
    ge = jax.grad(lambda p: mixtral.loss_fn(p, cfg_e, {"tokens": toks})[0])(params)
    gs = jax.grad(lambda p: mixtral.loss_fn(p, cfg_s, {"tokens": toks})[0])(params)
    for a, b_ in zip(jax.tree_util.tree_leaves(ge), jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(np.array(a), np.array(b_),
                                   rtol=5e-4, atol=1e-5)


def test_sliding_window_attention_parity():
    """window masking: XLA == flash (values + grads), and a window larger
    than the sequence equals full causal attention."""
    key = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(key, 3)
    b, s, hq, hkv, d = 1, 256, 4, 2, 64
    q = jax.random.normal(kq, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, hkv, d), jnp.float32)

    for w in (64, 100):
        ref = attention_xla(q, k, v, causal=True, window=w)
        got = flash_attention(q, k, v, causal=True, window=w,
                              block_q=64, block_k=64, interpret=True)
        np.testing.assert_allclose(np.array(got), np.array(ref),
                                   rtol=2e-3, atol=2e-3)
        gx = jax.grad(lambda q, k, v: jnp.sum(
            attention_xla(q, k, v, causal=True, window=w) ** 2
        ), argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True, window=w,
                            block_q=64, block_k=64, interpret=True) ** 2
        ), argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gx):
            np.testing.assert_allclose(np.array(a), np.array(b_),
                                       rtol=5e-3, atol=5e-3)

    full = attention_xla(q, k, v, causal=True)
    wide = attention_xla(q, k, v, causal=True, window=s + 7)
    np.testing.assert_allclose(np.array(wide), np.array(full), rtol=1e-6)


def test_sliding_window_with_q_offset_index_maps_stay_in_range():
    """Regression: with window <= q_offset (a later ring hop whose whole KV
    shard is out-of-window), _first_windowed_k_tile's floor lands past the
    last k tile; the kv index maps must clamp it back into range (on real
    TPU an out-of-range block index is undefined behavior — interpret mode
    hides it, so this asserts the map arithmetic directly, then checks
    numerics)."""
    from nexus_tpu.ops.attention import _first_windowed_k_tile

    block_q = block_k = 64
    sq = sk = 256
    window, off = 64, 256  # every q row's window floor is past this KV shard
    n_k_tiles = sk // block_k
    raws = [
        int(_first_windowed_k_tile(
            jnp.int32(i), block_q=block_q, block_k=block_k,
            q_offset=off, window=window,
        ))
        for i in range(sq // block_q)
    ]
    # the hazard this test pins down: unclamped floors past the last k tile
    assert max(raws) > n_k_tiles - 1, raws

    key = jax.random.PRNGKey(11)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, sq, 4, 64), jnp.float32)
    k = jax.random.normal(kk, (1, sk, 2, 64), jnp.float32)
    v = jax.random.normal(kv, (1, sk, 2, 64), jnp.float32)
    # q rows whose whole window lies past this KV shard are fully masked;
    # their output is ill-defined in a single-shard call (the
    # ring merge zeroes them via lse=-inf), so parity is asserted on the
    # in-window rows only: row i sees k iff off+i-window+1 <= sk-1
    valid = sk - 1 + window - 1 - off + 1  # rows [0, valid)
    assert 0 < valid < sq
    ref = attention_xla(q, k, v, causal=True, window=window, q_offset=off)
    got = flash_attention(q, k, v, causal=True, window=window, q_offset=off,
                          block_q=block_q, block_k=block_k, interpret=True)
    np.testing.assert_allclose(np.array(got)[:, :valid],
                               np.array(ref)[:, :valid],
                               rtol=2e-3, atol=2e-3)
    gx = jax.grad(lambda q, k, v: jnp.sum(
        attention_xla(q, k, v, causal=True, window=window,
                      q_offset=off)[:, :valid] ** 2
    ), argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True, window=window, q_offset=off,
                        block_q=block_q, block_k=block_k,
                        interpret=True)[:, :valid] ** 2
    ), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gx):
        np.testing.assert_allclose(np.array(a), np.array(b_),
                                   rtol=5e-3, atol=5e-3)


def test_sliding_window_decode_matches_forward():
    """Mixtral-style sliding window: KV-cache decode == full forward with
    the same window (both paths mask identically)."""
    from nexus_tpu.models import mixtral

    cfg = mixtral.config("tiny", dtype=jnp.float32, sliding_window=6)
    params = mixtral.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                                cfg.vocab_size)
    full, _ = mixtral.forward(params, cfg, tokens)
    cache = mixtral.init_kv_cache(cfg, 2, 16)
    # MoE capacity differs between prefill and single-token decode (see
    # test_mixtral_decode_and_generate) — compare the prefill path, which
    # routes the same token set as the forward
    pre, cache = mixtral.forward_decode(params, cfg, tokens, cache)
    np.testing.assert_allclose(np.array(pre), np.array(full),
                               rtol=5e-3, atol=5e-3)


def test_ring_attention_window_matches_dense_both_paths():
    """Sliding-window ring attention == dense windowed attention for BOTH
    block impls (flash inner kernels and the online-softmax path), values
    and gradients, including a window that statically truncates the ring
    (w <= s_local ⇒ only 2 of 8 blocks ever rotate)."""
    from nexus_tpu.ops.ring_attention import ring_attention

    try:
        from jax import shard_map
        smap = functools.partial(shard_map)
    except ImportError:
        from jax.experimental.shard_map import shard_map as smap  # noqa

    mesh = build_mesh(MeshPlan(sequence=8))
    b, s, h, d = 1, 64, 4, 16
    s_local = s // 8
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, 2, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, 2, d))
    seq_spec = P(None, "sequence", None, None)

    for w in (s_local - 2, s_local + 3, 3 * s_local):  # truncating + spanning
        ref = attention_xla(q, k, v, causal=True, window=w)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(
                attention_xla(q, k, v, causal=True, window=w) ** 2
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for impl in ("xla", "flash"):
            ring_fn = smap(
                functools.partial(
                    ring_attention, axis_name="sequence", causal=True,
                    block_impl=impl, window=w,
                ),
                mesh=mesh,
                in_specs=(seq_spec, seq_spec, seq_spec),
                out_specs=seq_spec,
                **({"check_vma": False} if hasattr(jax, "shard_map")
                   else {"check_rep": False}),
            )
            got = jax.jit(ring_fn)(q, k, v)
            np.testing.assert_allclose(
                np.array(got), np.array(ref), rtol=2e-3, atol=2e-3,
                err_msg=f"impl={impl} window={w}",
            )
            g_ring = jax.grad(
                lambda q, k, v: jnp.sum(ring_fn(q, k, v) ** 2),
                argnums=(0, 1, 2),
            )(q, k, v)
            for a, b_ in zip(g_ring, g_ref):
                np.testing.assert_allclose(
                    np.array(a), np.array(b_), rtol=5e-3, atol=5e-3,
                    err_msg=f"impl={impl} window={w}",
                )


def test_sliding_window_grid_compaction_parity():
    """When the window's tile footprint is far below the sequence's tile
    count, the flash kernels shrink their scan grids (attention.py::
    _window_tile_span) instead of enumerating-and-skipping — values and
    every gradient must still match XLA exactly, including with GQA,
    unequal q/k blocks, and a ring-style q_offset."""
    from nexus_tpu.ops.attention import _window_tile_span

    key = jax.random.PRNGKey(21)
    kq, kk, kv = jax.random.split(key, 3)

    # blocks 64, S=512, W=64: 8 k tiles full vs a 3-tile footprint — the
    # compacted path is definitely engaged
    assert _window_tile_span(64, 64, 64) == 3 < 512 // 64

    cases = [
        # (sq, sk, window, block_q, block_k, q_offset)
        (512, 512, 64, 64, 64, 0),
        (512, 512, 100, 64, 64, 0),     # window not tile-aligned
        (256, 512, 64, 64, 64, 256),    # ring hop: q in the second half
        (512, 512, 64, 64, 32, 0),      # unequal blocks: k-side compaction
        (512, 512, 48, 32, 64, 0),      # unequal blocks: q-side compaction
    ]
    for sq, sk, w, bq, bk, off in cases:
        q = jax.random.normal(kq, (1, sq, 4, 64), jnp.float32)
        k = jax.random.normal(kk, (1, sk, 2, 64), jnp.float32)
        v = jax.random.normal(kv, (1, sk, 2, 64), jnp.float32)
        ref = attention_xla(q, k, v, causal=True, window=w, q_offset=off)
        got = flash_attention(q, k, v, causal=True, window=w, q_offset=off,
                              block_q=bq, block_k=bk, interpret=True)
        np.testing.assert_allclose(
            np.array(got), np.array(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"fwd {(sq, sk, w, bq, bk, off)}",
        )
        gx = jax.grad(lambda q, k, v: jnp.sum(
            attention_xla(q, k, v, causal=True, window=w,
                          q_offset=off) ** 2
        ), argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True, window=w, q_offset=off,
                            block_q=bq, block_k=bk, interpret=True) ** 2
        ), argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gx):
            np.testing.assert_allclose(
                np.array(a), np.array(b_), rtol=5e-3, atol=5e-3,
                err_msg=f"grad {(sq, sk, w, bq, bk, off)}",
            )
