"""Rate limiter semantics (reference defaults: 30ms→5s exponential per item,
50/s burst 300 global bucket, combined via MaxOf — controller.go:257-260)."""

import pytest

from nexus_tpu.controller.ratelimit import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    MaxOfRateLimiter,
    default_controller_rate_limiter,
)


def test_exponential_backoff_doubles_and_caps():
    rl = ItemExponentialFailureRateLimiter(base_delay=0.030, max_delay=5.0)
    delays = [rl.when("a") for _ in range(12)]
    assert delays[0] == pytest.approx(0.030)
    assert delays[1] == pytest.approx(0.060)
    assert delays[2] == pytest.approx(0.120)
    assert delays[-1] == 5.0  # capped
    assert rl.num_requeues("a") == 12


def test_exponential_backoff_is_per_item():
    rl = ItemExponentialFailureRateLimiter(base_delay=0.030, max_delay=5.0)
    assert rl.when("a") == pytest.approx(0.030)
    assert rl.when("a") == pytest.approx(0.060)
    assert rl.when("b") == pytest.approx(0.030)


def test_forget_resets_backoff():
    rl = ItemExponentialFailureRateLimiter(base_delay=0.030, max_delay=5.0)
    rl.when("a")
    rl.when("a")
    rl.forget("a")
    assert rl.num_requeues("a") == 0
    assert rl.when("a") == pytest.approx(0.030)


def test_bucket_allows_burst_then_throttles():
    rl = BucketRateLimiter(rate=10.0, burst=5)
    delays = [rl.when("x") for _ in range(5)]
    assert all(d == 0.0 for d in delays)
    d6 = rl.when("x")
    assert d6 > 0.0
    d7 = rl.when("x")
    assert d7 > d6  # reservations stack into the future


def test_max_of_takes_worst_case():
    exp = ItemExponentialFailureRateLimiter(base_delay=1.0, max_delay=100.0)
    bucket = BucketRateLimiter(rate=1000.0, burst=1000)
    combined = MaxOfRateLimiter([exp, bucket])
    assert combined.when("a") == pytest.approx(1.0)  # exponential dominates
    assert combined.when("a") == pytest.approx(2.0)
    combined.forget("a")
    assert combined.num_requeues("a") == 0


def test_default_combination_matches_reference_defaults():
    rl = default_controller_rate_limiter()
    exp = rl.limiters[0]
    bucket = rl.limiters[1]
    assert exp.base_delay == pytest.approx(0.030)
    assert exp.max_delay == pytest.approx(5.0)
    assert bucket.rate == pytest.approx(50.0)
    assert bucket.burst == 300
