"""Test bootstrap.

Workload-plane tests run on a virtual 8-device CPU mesh so multi-chip
sharding is exercised without TPU hardware — the env vars must be set before
JAX is first imported anywhere.
"""

import os
import sys

# hard override: the ambient environment points JAX at the real TPU tunnel
# (JAX_PLATFORMS=axon); tests always run on the virtual 8-device CPU mesh.
# The sitecustomize imports jax before this file runs, so updating os.environ
# alone is not enough — update jax.config too (backends are initialized
# lazily, at first device use, so this still takes effect).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older JAX: option doesn't exist — the XLA_FLAGS
    # --xla_force_host_platform_device_count=8 override above already
    # provides the virtual 8-device CPU mesh
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

# Runtime sanitizers (docs/static-analysis.md): NEXUS_SANITIZE=1 wraps
# every ServingEngine.serve() with the pool-partition leak audit and the
# bounded-recompile audit, so ANY serving test that leaks a KV block or
# triggers a per-wave recompile storm fails loudly — not just the
# failover tests that assert the partition explicitly.
from nexus_tpu.testing import sanitizers as _sanitizers  # noqa: E402

if _sanitizers.sanitizers_enabled():
    _sanitizers.install()

# Workload-plane modules are compile-bound (minutes each on CPU) — they
# carry the `slow` marker so the default dev lane (`pytest -m "not slow"`)
# finishes in single-digit minutes while CI's full lane still runs and
# coverage-gates everything (VERDICT r3 weak #6).
_SLOW_MODULES = {
    "test_models",
    "test_multiprocess",
    "test_parallel",
    "test_property_convergence",
    "test_runtime",
    "test_serving",
    "test_train",
    "test_weights",
    "test_workload",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = os.path.splitext(os.path.basename(str(item.fspath)))[0]
        if mod in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
