"""jax_xla runtime: materializer manifests, entrypoint execution, and the
full BASELINE config #2 e2e — a template with a runtime block synced by the
controller to a local shard and *executed* there (template → running JAX job).
"""

import json
import time

import jax
import pytest

from nexus_tpu.api.runtime_spec import (
    JaxXlaRuntime,
    ModelRef,
    ParallelismSpec,
    TpuSliceSpec,
    TrainSpec,
)
from nexus_tpu.api.template import NexusAlgorithmTemplate
from nexus_tpu.api.types import ConfigMap
from nexus_tpu.cluster.store import ClusterStore, NotFoundError
from nexus_tpu.controller.controller import Controller
from nexus_tpu.runtime.entrypoints import run_template_runtime
from nexus_tpu.runtime.launcher import LocalLauncher
from nexus_tpu.runtime.materializer import materialize_job
from nexus_tpu.shards.shard import Shard
from nexus_tpu.utils.telemetry import StatsdClient
from tests.test_controller_sync import NS, make_template


def runtime_block(**kw):
    defaults = dict(
        mode="train",
        model=ModelRef(family="mlp", preset="tiny"),
        tpu=TpuSliceSpec(accelerator="v5p", topology="2x2x2", slice_count=1),
        parallelism=ParallelismSpec(data=2, fsdp=2, tensor=2),
        train=TrainSpec(batch_size=32, steps=12, learning_rate=1e-2),
    )
    defaults.update(kw)
    return JaxXlaRuntime(**defaults)


def template_with_runtime(name="tpu-algo", **kw):
    tmpl = make_template(name)
    tmpl.spec.runtime = runtime_block(**kw)
    return tmpl


# ----------------------------------------------------------------- manifests


def test_materializer_emits_tpu_scheduling():
    tmpl = template_with_runtime()
    tmpl.metadata.uid = "uid-test"
    jobs = materialize_job(tmpl, shard_name="shard0")
    assert len(jobs) == 1
    job = jobs[0]

    pod = job["spec"]["template"]["spec"]
    # the north-star assertions: TPU selectors + google.com/tpu, no GPU/NCCL
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x2x2"
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5p-slice"
    res = pod["containers"][0]["resources"]["limits"]
    assert res["google.com/tpu"] == "4"  # chips per host
    assert "nvidia.com/gpu" not in res
    env_names = {e["name"] for e in pod["containers"][0]["env"]}
    assert "NEXUS_RUNTIME_SPEC" in env_names
    assert "JAX_COORDINATOR_ADDRESS" in env_names
    assert not any("NCCL" in n for n in env_names)

    # one indexed completion per host: 8 chips / 4 per host = 2
    assert job["spec"]["completions"] == 2
    assert job["spec"]["parallelism"] == 2
    assert job["spec"]["completionMode"] == "Indexed"
    # job owned by the template (GC linkage)
    assert job["metadata"]["ownerReferences"][0]["uid"] == "uid-test"


def test_materializer_multislice_emits_one_job_per_slice():
    tmpl = template_with_runtime(
        tpu=TpuSliceSpec(accelerator="v5e", topology="2x2", slice_count=2),
        parallelism=ParallelismSpec(data=2, fsdp=2, tensor=2),
    )
    jobs = materialize_job(tmpl)
    assert len(jobs) == 2
    assert jobs[0]["metadata"]["name"] == "tpu-algo-s0"
    assert jobs[1]["metadata"]["name"] == "tpu-algo-s1"


def test_materializer_rejects_invalid_runtime():
    tmpl = template_with_runtime(
        parallelism=ParallelismSpec(data=3)  # 3 != 8 chips
    )
    with pytest.raises(ValueError, match="parallelism axes product"):
        materialize_job(tmpl)


def test_materializer_requires_runtime():
    with pytest.raises(ValueError, match="no jax_xla runtime"):
        materialize_job(make_template())


# ---------------------------------------------------------------- entrypoint


def test_run_template_runtime_mlp_train():
    metrics = run_template_runtime(runtime_block())
    assert metrics["mode"] == "train"
    assert metrics["final_loss"] is not None
    assert metrics["final_loss"] < 1.0
    assert metrics["n_devices"] == 8
    assert metrics["steps_per_sec"] > 0


def test_run_template_runtime_llama_train_reports_mfu():
    metrics = run_template_runtime(
        runtime_block(
            model=ModelRef(family="llama", preset="tiny",
                           overrides={"dtype": "float32"}),
            train=TrainSpec(batch_size=8, seq_len=32, steps=4),
        )
    )
    assert metrics["tokens_per_sec"] > 0
    assert metrics["tokens_per_sec_per_chip"] > 0
    assert 0 <= metrics["mfu"] < 1
    assert metrics["param_count"] > 0


def test_run_template_runtime_speculative_infer():
    """infer with a draft model routes through speculative_generate and
    reports the speculative metrics (product path for the feature)."""
    from nexus_tpu.api.runtime_spec import InferSpec

    metrics = run_template_runtime(
        runtime_block(
            model=ModelRef(family="llama", preset="tiny",
                           overrides={"dtype": "float32"}),
            mode="infer",
            train=TrainSpec(batch_size=1, seq_len=64, steps=1),
            infer=InferSpec(
                prompt_length=8, max_new_tokens=12, iterations=1,
                draft=ModelRef(family="llama", preset="tiny",
                               overrides={"dtype": "float32"}),
                num_speculative=3,
            ),
        )
    )
    assert metrics["mode"] == "infer"
    assert metrics["speculative"] is True
    assert metrics["num_speculative"] == 3
    assert metrics["decode_tokens_per_sec"] > 0
    assert metrics["new_tokens"] == 12
    assert metrics["rounds"] >= 1
    assert 0.0 <= metrics["acceptance_rate"] <= 1.0
    assert 0.0 < metrics["target_forwards_per_token"] <= 1.0


def test_run_template_runtime_infer_prompt_token_ids():
    """infer.promptTokenIds: explicit ids (no tokenizer) drive the
    decode — the prompt length follows the id list, out-of-vocab ids
    are rejected fast, and the text-prompt combination is a spec
    error."""
    from nexus_tpu.api.runtime_spec import InferSpec

    ids = [3, 1, 4, 1, 5, 9, 2, 6]
    metrics = run_template_runtime(
        runtime_block(
            model=ModelRef(family="llama", preset="tiny",
                           overrides={"dtype": "float32"}),
            mode="infer",
            train=TrainSpec(batch_size=1, seq_len=64, steps=1),
            infer=InferSpec(
                prompt_token_ids=ids, max_new_tokens=6, iterations=1,
            ),
        )
    )
    assert metrics["prompt_len"] == len(ids)
    assert metrics["new_tokens"] == 6

    import pytest as _pytest

    bad = runtime_block(
        model=ModelRef(family="llama", preset="tiny",
                       overrides={"dtype": "float32"}),
        mode="infer",
        train=TrainSpec(batch_size=1, seq_len=64, steps=1),
        infer=InferSpec(prompt_token_ids=[999999], max_new_tokens=4),
    )
    with _pytest.raises(ValueError, match="outside vocab"):
        run_template_runtime(bad)

    both = runtime_block(
        model=ModelRef(family="llama", preset="tiny"),
        mode="infer",
        infer=InferSpec(prompt="hi", prompt_token_ids=[1, 2]),
    )
    assert any("mutually exclusive" in e for e in both.validate())

    # round-trips through the YAML dict form
    rt = runtime_block(
        mode="infer",
        infer=InferSpec(prompt_token_ids=ids),
    )
    d = rt.to_dict()
    assert d["infer"]["promptTokenIds"] == ids
    assert type(rt).from_dict(d).infer.prompt_token_ids == ids


def test_run_template_runtime_prompt_lookup_infer():
    """infer with promptLookupNgram routes through prompt_lookup_generate
    (draft-free speculation) and reports the speculative metrics."""
    from nexus_tpu.api.runtime_spec import InferSpec

    metrics = run_template_runtime(
        runtime_block(
            model=ModelRef(family="llama", preset="tiny",
                           overrides={"dtype": "float32"}),
            mode="infer",
            train=TrainSpec(batch_size=2, seq_len=64, steps=1),
            infer=InferSpec(
                prompt_length=8, max_new_tokens=12, iterations=1,
                num_speculative=3, prompt_lookup_ngram=2,
            ),
        )
    )
    assert metrics["mode"] == "infer"
    assert metrics["speculative"] is True
    assert metrics["speculative_kind"] == "prompt_lookup"
    assert metrics["prompt_lookup_ngram"] == 2
    assert metrics["decode_tokens_per_sec"] > 0
    assert metrics["new_tokens"] == 12  # per-row decode budget
    assert metrics["rounds"] >= 1
    assert 0.0 <= metrics["acceptance_rate"] <= 1.0
    assert 0.0 < metrics["target_forwards_per_token"] <= 1.0
    assert metrics["lookup_hit_rounds"] >= 0


def test_prompt_lookup_spec_validation():
    """promptLookupNgram: mutually exclusive with a draft model, greedy
    only, and round-trips through the YAML dict form."""
    from nexus_tpu.api.runtime_spec import InferSpec

    rt = runtime_block(
        model=ModelRef(family="llama", preset="tiny"),
        mode="infer",
        infer=InferSpec(
            prompt_lookup_ngram=3,
            draft=ModelRef(family="llama", preset="tiny"),
        ),
    )
    errs = rt.validate()
    assert any("mutually exclusive" in e for e in errs), errs

    rt = runtime_block(
        model=ModelRef(family="llama", preset="tiny"),
        mode="infer",
        infer=InferSpec(prompt_lookup_ngram=3, temperature=0.7),
    )
    errs = rt.validate()
    assert any("temperature" in e for e in errs), errs

    rt = runtime_block(
        model=ModelRef(family="llama", preset="tiny"),
        mode="infer",
        infer=InferSpec(prompt_lookup_ngram=3, num_speculative=5),
    )
    assert rt.validate() == []
    d = rt.to_dict()
    assert d["infer"]["promptLookupNgram"] == 3
    rt2 = type(rt).from_dict(d)
    assert rt2.infer.prompt_lookup_ngram == 3
    assert rt2.infer.num_speculative == 5


def test_hbm_budget_feasibility_gate():
    """Paper-math HBM admission (VERDICT r3 item 3): an 8B train on a
    single v5e is rejected with the budget breakdown; the same model
    FSDP-sharded across a v5p-64 (the BASELINE north-star config)
    passes; unsharded 8B training on v5p-64 (96 GB/chip of state vs
    95 GB HBM) is rejected too."""
    from nexus_tpu.api.runtime_spec import TpuSliceSpec

    # 8B on one v5e chip: ~96 GB of train state vs 16 GB — infeasible
    rt = runtime_block(
        model=ModelRef(family="llama", preset="8b"),
        tpu=TpuSliceSpec(accelerator="v5e", topology="1x1", slice_count=1),
        parallelism=ParallelismSpec(),
        train=TrainSpec(batch_size=8, seq_len=2048, steps=1, remat=True),
    )
    errs = rt.validate()
    assert any("HBM budget infeasible" in e for e in errs), errs
    budget = rt.hbm_budget_gb()
    assert budget["state_gb"] > 16, budget

    # north star: 8B FSDP over v5p-64 — feasible with remat
    rt = runtime_block(
        model=ModelRef(family="llama", preset="8b",
                       overrides={"remat": True,
                                  "remat_policy": "dots_attn"}),
        tpu=TpuSliceSpec(accelerator="v5p", topology="4x4x4",
                         slice_count=1),
        parallelism=ParallelismSpec(fsdp=64),
        train=TrainSpec(batch_size=64, seq_len=8192, steps=1, remat=True),
    )
    assert rt.validate() == [], rt.validate()
    budget = rt.hbm_budget_gb()
    assert budget["total_gb"] < 95, budget

    # pure DP on v5p-64 replicates the full 8B state per chip (~90 GB)
    # and, without remat, the activations push past 95 GB: rejected
    rt = runtime_block(
        model=ModelRef(family="llama", preset="8b"),
        tpu=TpuSliceSpec(accelerator="v5p", topology="4x4x4",
                         slice_count=1),
        parallelism=ParallelismSpec(data=64),
        train=TrainSpec(batch_size=64, seq_len=2048, steps=1,
                        remat=False),
    )
    errs = rt.validate()
    assert any("HBM budget infeasible" in e for e in errs), errs

    # the single-chip bench config stays feasible (remat, 16 GB v5e)
    rt = runtime_block(
        model=ModelRef(family="llama", preset="400m",
                       overrides={"remat": True, "remat_policy": "dots"}),
        tpu=TpuSliceSpec(accelerator="v5e", topology="1x1", slice_count=1),
        parallelism=ParallelismSpec(),
        train=TrainSpec(batch_size=8, seq_len=2048, steps=1, remat=True),
    )
    assert rt.validate() == [], rt.validate()

    # infer mode budgets params + KV cache, not optimizer state: 8B
    # inference fits a v5e-8 slice with the cache tensor-sharded
    rt = runtime_block(
        mode="infer",
        model=ModelRef(family="llama", preset="8b"),
        tpu=TpuSliceSpec(accelerator="v5e", topology="2x4", slice_count=1),
        parallelism=ParallelismSpec(tensor=8),
        train=TrainSpec(batch_size=8, seq_len=128),
    )
    assert rt.validate() == [], rt.validate()
    budget = rt.hbm_budget_gb()
    assert "kv_cache_gb" in budget and budget["total_gb"] < 16, budget


def test_hbm_budget_expert_axis_only_shards_moe_experts():
    """ADVICE r4 #1: the expert axis shards ONLY MoE expert weights. A
    dense llama budget is identical at expert=1 and expert=8; a mixtral
    budget divides the expert FF weights by the expert axis while the
    attention/embedding/router params stay replicated across it."""
    from nexus_tpu.api.runtime_spec import TpuSliceSpec
    from nexus_tpu.models.registry import get_family

    base = dict(
        tpu=TpuSliceSpec(accelerator="v5p", topology="2x2x2",
                         slice_count=1),
        train=TrainSpec(batch_size=8, seq_len=512, steps=1, remat=True),
    )
    dense1 = runtime_block(
        model=ModelRef(family="llama", preset="400m"),
        parallelism=ParallelismSpec(), **base,
    ).hbm_budget_gb()
    dense8 = runtime_block(
        model=ModelRef(family="llama", preset="400m"),
        parallelism=ParallelismSpec(expert=8), **base,
    ).hbm_budget_gb()
    assert dense8["state_gb"] == pytest.approx(dense1["state_gb"]), (
        dense1, dense8,
    )

    moe1 = runtime_block(
        model=ModelRef(family="mixtral", preset="8x7b"),
        parallelism=ParallelismSpec(), **base,
    ).hbm_budget_gb()
    moe8 = runtime_block(
        model=ModelRef(family="mixtral", preset="8x7b"),
        parallelism=ParallelismSpec(expert=8), **base,
    ).hbm_budget_gb()
    assert moe8["state_gb"] < moe1["state_gb"]
    # exact split: dense params replicated, expert params / 8
    cfg = get_family("mixtral").config("8x7b")
    expert_params = cfg.n_layers * cfg.n_experts * 3 * cfg.d_model * cfg.d_ff
    dense_params = cfg.param_count() - expert_params
    expected = (dense_params + expert_params / 8) * (2 * 2 + 8) / 1024 ** 3
    assert moe8["state_gb"] == pytest.approx(expected, rel=1e-3)


def test_hbm_gate_modes(monkeypatch):
    """ADVICE r4 #2: hbmGate='warn' admits an HBM-infeasible template
    with a logged warning instead of rejecting; 'off' skips the check;
    an unknown mode is itself a validation error; NEXUS_HBM_GATE
    overrides the spec for operators."""
    from nexus_tpu.api.runtime_spec import TpuSliceSpec

    monkeypatch.delenv("NEXUS_HBM_GATE", raising=False)

    def infeasible(**kw):
        return runtime_block(
            model=ModelRef(family="llama", preset="8b"),
            tpu=TpuSliceSpec(accelerator="v5e", topology="1x1",
                             slice_count=1),
            parallelism=ParallelismSpec(),
            train=TrainSpec(batch_size=8, seq_len=2048, steps=1,
                            remat=True),
            **kw,
        )

    assert any("HBM budget infeasible" in e
               for e in infeasible().validate())
    assert infeasible(hbm_gate="warn").validate() == []
    assert infeasible(hbm_gate="off").validate() == []
    errs = infeasible(hbm_gate="sometimes").validate()
    assert any("hbmGate" in e for e in errs), errs
    # env override beats the spec field, both directions
    monkeypatch.setenv("NEXUS_HBM_GATE", "warn")
    assert infeasible().validate() == []
    monkeypatch.setenv("NEXUS_HBM_GATE", "error")
    assert any("HBM budget infeasible" in e
               for e in infeasible(hbm_gate="warn").validate())
    # round-trips through the wire format
    rt = infeasible(hbm_gate="warn")
    assert JaxXlaRuntime.from_dict(rt.to_dict()).hbm_gate == "warn"


def test_comm_budget_8b_north_star_ici_feasible():
    """VERDICT r4 item 8: the 8B/v5p-64 north-star config's projected
    FSDP comm/compute ratio is < 1 — ICI all-gather fits under the
    compute at 35% MFU (paper-math companion to the HBM gate; model
    documented in docs/PERF.md)."""
    from nexus_tpu.api.runtime_spec import TpuSliceSpec

    rt = runtime_block(
        model=ModelRef(family="llama", preset="8b",
                       overrides={"remat": True,
                                  "remat_policy": "dots_attn"}),
        tpu=TpuSliceSpec(accelerator="v5p", topology="4x4x4",
                         slice_count=1),
        parallelism=ParallelismSpec(fsdp=64),
        train=TrainSpec(batch_size=64, seq_len=8192, steps=1, remat=True),
    )
    b = rt.comm_budget_per_step(target_mfu=0.35)
    assert b is not None
    assert b["comm_compute_ratio"] < 1.0, b
    # the crossing point is far below the configured 8192 tokens/chip
    assert b["breakeven_tokens_per_chip"] < 8192 / 4, b
    # not applicable without an fsdp axis or off train mode
    assert runtime_block(
        model=ModelRef(family="llama", preset="8b"),
        tpu=TpuSliceSpec(accelerator="v5p", topology="4x4x4",
                         slice_count=1),
        parallelism=ParallelismSpec(data=64),
        train=TrainSpec(batch_size=64, seq_len=8192, steps=1),
    ).comm_budget_per_step() is None


def test_run_template_runtime_gptneox_train():
    """The gptneox family trains through the product runtime path on the
    8-device mesh — same contract as the other LM families."""
    metrics = run_template_runtime(
        runtime_block(
            model=ModelRef(family="gptneox", preset="tiny",
                           overrides={"dtype": "float32"}),
            train=TrainSpec(batch_size=8, seq_len=32, steps=4),
        )
    )
    assert metrics["mode"] == "train"
    assert metrics["tokens_per_sec"] > 0
    assert metrics["final_loss"] is not None
    assert 0 <= metrics["mfu"] < 1


def test_run_template_runtime_pipeline_parallel_matches_plain():
    """VERDICT r1 item 3: a template with pipeline=2 must actually train
    through the GPipe path, with loss parity vs the non-PP path."""
    common = dict(
        model=ModelRef(family="llama", preset="tiny",
                       overrides={"dtype": "float32", "attn_impl": "xla"}),
        tpu=TpuSliceSpec(accelerator="v5e", topology="2x4", slice_count=1),
        train=TrainSpec(batch_size=8, seq_len=32, steps=3),
    )
    pp = run_template_runtime(
        runtime_block(
            parallelism=ParallelismSpec(pipeline=2, data=4), **common
        )
    )
    gpipe = run_template_runtime(
        runtime_block(
            parallelism=ParallelismSpec(
                pipeline=2, data=4, pipeline_schedule="gpipe"
            ),
            **common,
        )
    )
    plain = run_template_runtime(
        runtime_block(parallelism=ParallelismSpec(data=4, fsdp=2), **common)
    )
    assert pp["final_loss"] is not None
    # identical init (same seed) + identical data stream → first-step loss
    # must agree across schedules up to float reassociation (default
    # schedule is 1F1B; gpipe is the explicit fallback)
    assert abs(pp["loss_history"][0] - plain["loss_history"][0]) < 1e-3, (
        pp["loss_history"],
        plain["loss_history"],
    )
    assert abs(gpipe["loss_history"][0] - plain["loss_history"][0]) < 1e-3, (
        gpipe["loss_history"],
        plain["loss_history"],
    )


def test_run_template_runtime_bench_candidate_path():
    """The exact config shape bench.py's top sweep candidates run (remat
    dots + vocab-chunked CE) must train end-to-end — insurance that the
    driver's on-TPU bench can't hit an untested combination."""
    metrics = run_template_runtime(
        runtime_block(
            model=ModelRef(
                family="llama", preset="tiny",
                overrides={
                    "dtype": "float32",
                    "remat": True,
                    "remat_policy": "dots",
                    "ce_chunk": 96,
                    "attn_impl": "xla",
                },
            ),
            train=TrainSpec(batch_size=8, seq_len=32, steps=3),
        )
    )
    import math

    assert math.isfinite(metrics["final_loss"])
    assert metrics["tokens_per_sec"] > 0


def test_run_template_runtime_pipeline_rejects_unsupported():
    with pytest.raises(ValueError, match="pipeline parallelism"):
        run_template_runtime(
            runtime_block(
                model=ModelRef(family="mlp", preset="tiny"),
                tpu=TpuSliceSpec(accelerator="v5e", topology="2x4"),
                parallelism=ParallelismSpec(pipeline=2, data=4),
            )
        )
    with pytest.raises(ValueError, match="not divisible"):
        run_template_runtime(
            runtime_block(
                model=ModelRef(
                    family="llama", preset="tiny",
                    overrides={"dtype": "float32", "n_layers": 3},
                ),
                tpu=TpuSliceSpec(accelerator="v5e", topology="2x4"),
                parallelism=ParallelismSpec(pipeline=2, data=4),
                train=TrainSpec(batch_size=8, seq_len=32, steps=2),
            )
        )


def test_train_checkpoint_infer_roundtrip(tmp_path):
    """VERDICT r1 item 4: weights trained + checkpointed by the train
    runtime load into the infer runtime (not random init), with the KV
    cache sharded over the mesh and repeated timed decodes."""
    from nexus_tpu.api.runtime_spec import CheckpointSpec, InferSpec

    ckpt_dir = str(tmp_path / "ckpt")
    common = dict(
        model=ModelRef(family="llama", preset="tiny",
                       overrides={"dtype": "float32"}),
        tpu=TpuSliceSpec(accelerator="v5e", topology="2x4", slice_count=1),
        parallelism=ParallelismSpec(data=2, fsdp=2, tensor=2),
        checkpoint=CheckpointSpec(enabled=True, directory=ckpt_dir,
                                  interval_steps=2),
    )
    train_metrics = run_template_runtime(
        runtime_block(
            mode="train",
            train=TrainSpec(batch_size=8, seq_len=32, steps=3),
            **common,
        )
    )
    assert train_metrics["checkpoint_saved"]

    infer_metrics = run_template_runtime(
        runtime_block(
            mode="infer",
            train=TrainSpec(batch_size=2, seq_len=32, steps=1),
            infer=InferSpec(prompt_length=8, max_new_tokens=24, iterations=2),
            **common,
        )
    )
    assert infer_metrics["weights_loaded"] is True
    assert infer_metrics["restored_step"] >= 1
    assert infer_metrics["decode_tokens_per_sec"] > 0
    assert infer_metrics["new_tokens"] == 24
    assert len(infer_metrics["iteration_seconds"]) == 2


def test_speculative_infer_loads_draft_checkpoint(tmp_path):
    """A trained draft checkpoint restores into the speculative infer path:
    with the SAME weights trained for target and draft, acceptance is
    perfect and draft_weights_loaded reports true."""
    from nexus_tpu.api.runtime_spec import CheckpointSpec, InferSpec

    ckpt_dir = str(tmp_path / "draft-ckpt")
    common = dict(
        model=ModelRef(family="llama", preset="tiny",
                       overrides={"dtype": "float32"}),
        tpu=TpuSliceSpec(accelerator="v5e", topology="2x4", slice_count=1),
        parallelism=ParallelismSpec(data=2, fsdp=2, tensor=2),
    )
    train_metrics = run_template_runtime(
        runtime_block(
            mode="train",
            train=TrainSpec(batch_size=8, seq_len=32, steps=3),
            checkpoint=CheckpointSpec(enabled=True, directory=ckpt_dir,
                                      interval_steps=2),
            **common,
        )
    )
    assert train_metrics["checkpoint_saved"]

    infer_metrics = run_template_runtime(
        runtime_block(
            mode="infer",
            train=TrainSpec(batch_size=1, seq_len=32, steps=1),
            infer=InferSpec(
                prompt_length=8, max_new_tokens=12, iterations=1,
                draft=ModelRef(family="llama", preset="tiny",
                               overrides={"dtype": "float32"}),
                num_speculative=3,
                draft_checkpoint_directory=ckpt_dir,
            ),
            # target loads the same checkpoint -> draft == target
            checkpoint=CheckpointSpec(enabled=True, directory=ckpt_dir),
            **common,
        )
    )
    assert infer_metrics["weights_loaded"] is True
    assert infer_metrics["draft_weights_loaded"] is True
    # identical weights -> the draft always matches the target
    assert infer_metrics["acceptance_rate"] == 1.0


def test_infer_long_decode_512_tokens():
    """>=512-token decode through the scanned cache path (the honest
    config-#3 shape, scaled to the tiny preset)."""
    from nexus_tpu.api.runtime_spec import InferSpec

    metrics = run_template_runtime(
        runtime_block(
            mode="infer",
            model=ModelRef(
                family="llama", preset="tiny",
                overrides={"dtype": "float32", "max_seq_len": 544},
            ),
            tpu=TpuSliceSpec(accelerator="v5e", topology="2x4", slice_count=1),
            parallelism=ParallelismSpec(data=2, fsdp=2, tensor=2),
            train=TrainSpec(batch_size=2, seq_len=32, steps=1),
            infer=InferSpec(prompt_length=16, max_new_tokens=512, iterations=1),
        )
    )
    assert metrics["new_tokens"] == 512
    assert metrics["weights_loaded"] is False
    assert metrics["decode_tokens_per_sec"] > 0


# ------------------------------------------------------- the config #2 e2e


def wait_for(pred, timeout=90.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if pred():
                return True
        except NotFoundError:
            pass
        time.sleep(interval)
    return False


def test_e2e_template_synced_and_executed():
    """BASELINE config #2: declare a template with a jax_xla MLP runtime in
    the controller cluster → controller syncs it to the local shard → the
    shard's launcher materializes + executes it → result recorded."""
    controller_store = ClusterStore("controller")
    shard_store = ClusterStore("shard0")
    shard = Shard("e2e", "shard0", shard_store)
    controller = Controller(
        controller_store, [shard], statsd=StatsdClient("test"), resync_period=1.0
    )
    launcher = LocalLauncher(shard_store)
    controller.run(workers=2)
    launcher.start()
    try:
        controller_store.create(template_with_runtime())

        # template lands on the shard via the controller
        assert wait_for(
            lambda: shard_store.get(NexusAlgorithmTemplate.KIND, NS, "tpu-algo")
            is not None
        ), "template never synced to shard"

        # launcher executes it and records the result
        assert wait_for(
            lambda: json.loads(
                shard_store.get(ConfigMap.KIND, NS, "tpu-algo-result").data["metrics"]
            )["final_loss"] is not None
        ), "job never completed on shard"

        result = shard_store.get(ConfigMap.KIND, NS, "tpu-algo-result")
        assert result.data["phase"] == "Succeeded"
        metrics = json.loads(result.data["metrics"])
        assert metrics["final_loss"] < 1.0
        manifest = json.loads(result.data["jobManifest"])
        assert (
            manifest["spec"]["template"]["spec"]["nodeSelector"][
                "cloud.google.com/gke-tpu-topology"
            ]
            == "2x2x2"
        )
        # completion event emitted
        assert any(
            e.reason == "JobCompleted" for e in launcher.recorder.events
        )
        # workload phase round-trip: controller applied the Job, launcher
        # (as local kubelet) drove its status, controller wrote it back into
        # template status (VERDICT r1 item 2)
        assert wait_for(
            lambda: controller_store.get(
                NexusAlgorithmTemplate.KIND, NS, "tpu-algo"
            ).status.workload_phase
            == "Succeeded"
        ), "workload phase never propagated to template status"
    finally:
        launcher.stop()
        controller.stop()


def test_launcher_reruns_on_spec_change_only():
    store = ClusterStore("shard")
    launcher = LocalLauncher(store)
    launcher.start()
    try:
        tmpl = template_with_runtime()
        store.create(tmpl)
        assert wait_for(
            lambda: store.get(ConfigMap.KIND, NS, "tpu-algo-result").data["phase"]
            == "Succeeded"
        )
        gen1 = store.get(ConfigMap.KIND, NS, "tpu-algo-result").data["generation"]

        # status-only touch: no re-run
        store.update_status(store.get(NexusAlgorithmTemplate.KIND, NS, "tpu-algo"))
        launcher.wait_idle()
        assert (
            store.get(ConfigMap.KIND, NS, "tpu-algo-result").data["generation"]
            == gen1
        )

        # spec change: re-run with new generation
        fresh = store.get(NexusAlgorithmTemplate.KIND, NS, "tpu-algo")
        fresh.spec.runtime.train.steps = 5
        store.update(fresh)
        assert wait_for(
            lambda: store.get(ConfigMap.KIND, NS, "tpu-algo-result").data[
                "generation"
            ]
            != gen1
        ), "spec change never triggered a re-run"
    finally:
        launcher.stop()


def test_launcher_records_failure():
    store = ClusterStore("shard")
    launcher = LocalLauncher(store)
    launcher.start()
    try:
        tmpl = template_with_runtime(
            model=ModelRef(family="nonexistent-family", preset="tiny")
        )
        store.create(tmpl)
        # the job thread is registered synchronously by create()'s watch
        # dispatch — wait for it to finish rather than racing a fixed
        # deadline against machine load (this test flaked under full-suite
        # CPU contention)
        assert launcher.wait_idle(timeout=180), "job thread never finished"
        assert wait_for(
            lambda: store.get(ConfigMap.KIND, NS, "tpu-algo-result").data["phase"]
            == "Failed",
            timeout=10,
        )
        assert any(e.reason == "JobFailed" for e in launcher.recorder.events)
    finally:
        launcher.stop()


def test_materializer_multislice_coordinator_resolves():
    """Regression: every slice's JAX_COORDINATOR_ADDRESS must point at pod 0
    of slice 0 under slice 0's OWN subdomain (pod-subdomain DNS records only
    exist under the pod's job-named subdomain)."""
    tmpl = template_with_runtime(
        tpu=TpuSliceSpec(accelerator="v5e", topology="2x2", slice_count=2),
        parallelism=ParallelismSpec(data=2, fsdp=2, tensor=2),
    )
    jobs = materialize_job(tmpl)
    for job in jobs:
        env = {
            e["name"]: e["value"]
            for e in job["spec"]["template"]["spec"]["containers"][0]["env"]
        }
        assert env["JAX_COORDINATOR_ADDRESS"] == "tpu-algo-s0-0.tpu-algo-s0:8476"
    # the subdomains need headless Services to get DNS records
    from nexus_tpu.runtime.materializer import materialize_headless_service

    svcs = materialize_headless_service(tmpl)
    assert [s["metadata"]["name"] for s in svcs] == ["tpu-algo-s0", "tpu-algo-s1"]
    assert all(s["spec"]["clusterIP"] == "None" for s in svcs)


def test_launcher_update_during_running_job_not_dropped():
    """Regression: a spec update arriving while the previous job is still
    running must be executed once that job finishes (not silently dropped)."""
    store = ClusterStore("shard")
    launcher = LocalLauncher(store)
    launcher.start()
    try:
        tmpl = template_with_runtime(
            train=TrainSpec(batch_size=256, steps=60, learning_rate=1e-2)
        )
        store.create(tmpl)
        # immediately update the spec — the first job is still running
        fresh = store.get(NexusAlgorithmTemplate.KIND, NS, "tpu-algo")
        fresh.spec.runtime.train.steps = 3
        updated = store.update(fresh)
        final_gen = str(updated.metadata.generation)
        assert wait_for(
            lambda: store.get(ConfigMap.KIND, NS, "tpu-algo-result").data[
                "generation"
            ]
            == final_gen,
            timeout=120.0,
        ), "updated generation never ran"
    finally:
        launcher.stop()


def test_moe_dispatch_auto_resolves_from_mesh():
    """dispatch_impl='auto': the runtime picks scatter only where it
    was measured (a single-device program, 2.45x at step level) and
    einsum's known-good SPMD partitionings on any sharded mesh; an
    explicit pin wins either way. The RESOLVED impl is surfaced in the
    metrics so this resolution is pinned by assertion, not inference."""
    from nexus_tpu.api.runtime_spec import ParallelismSpec

    def run(parallelism, overrides=None):
        return run_template_runtime(
            runtime_block(
                model=ModelRef(family="mixtral", preset="tiny",
                               overrides={"dtype": "float32",
                                          **(overrides or {})}),
                parallelism=parallelism,
                train=TrainSpec(batch_size=8, seq_len=16, steps=2),
            )
        )

    # ANY sharded mesh (EP or not) resolves to einsum — scatter's 2.45x
    # was measured single-device and a sharded scatter's partitioning is
    # compiler-dependent; only a 1-device program auto-selects scatter
    sharded = run(ParallelismSpec(data=2, fsdp=2, tensor=2))
    assert sharded["moe_dispatch"] == "einsum"
    assert sharded["final_loss"] is not None

    ep = run(ParallelismSpec(data=2, expert=4))
    assert ep["moe_dispatch"] == "einsum"
    assert ep["final_loss"] is not None

    single = run_template_runtime(
        runtime_block(
            model=ModelRef(family="mixtral", preset="tiny",
                           overrides={"dtype": "float32"}),
            tpu=TpuSliceSpec(accelerator="v5e", topology="1x1",
                             slice_count=1),
            parallelism=ParallelismSpec(),
            train=TrainSpec(batch_size=4, seq_len=16, steps=2),
        ),
        devices=jax.devices()[:1],
    )
    assert single["moe_dispatch"] == "scatter"
    assert single["final_loss"] is not None

    pinned = run(ParallelismSpec(data=2, expert=4),
                 overrides={"dispatch_impl": "scatter"})
    assert pinned["moe_dispatch"] == "scatter"  # explicit pin always wins
    assert pinned["final_loss"] is not None
