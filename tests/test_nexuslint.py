"""nexuslint + runtime-sanitizer coverage (fast CPU lane).

Three layers, mirroring how the gate is trusted:

  1. per-rule fixtures — a violating snippet and its clean twin, so every
     rule family demonstrably fires AND demonstrably stays quiet;
  2. machinery — suppression comments, file-level disables, config
     scoping, CLI exit codes;
  3. the repo gate itself — ``make analyze`` must pass on the tree
     (asserted here through the same API the CLI uses), and the runtime
     sanitizers must catch seeded pool leaks / recompile storms while
     passing a real stub-engine serve.
"""

import os
import textwrap

import pytest

from tools.nexuslint import __main__ as nexuslint_cli
from tools.nexuslint.core import LintConfig, lint_paths, lint_source, load_config

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ids(findings):
    return [f.rule_id for f in findings]


def _lint(src, path="mod.py", config=None, select=None):
    return lint_source(path, textwrap.dedent(src), config, select=select)


# ---------------------------------------------------------------------------
# NX-CLOCK


CLOCK_VIOLATION = """
    import time

    class Detector:
        def __init__(self, clock=time.monotonic):
            self.clock = clock

        def probe(self):
            return time.monotonic()  # the drift the rule exists for
"""


def test_clock_rule_fires_on_direct_read_in_disciplined_module():
    findings = _lint(CLOCK_VIOLATION, select=["NX-CLOCK"])
    assert _ids(findings) == ["NX-CLOCK001"]
    assert "time.monotonic" in findings[0].message


def test_clock_rule_ignores_undisciplined_modules():
    src = """
        import time

        def stamp():
            return time.monotonic()
    """
    assert _lint(src, select=["NX-CLOCK"]) == []


def test_clock_rule_allows_default_value_references():
    """``clock=time.monotonic`` as a default is the injection idiom, not
    a violation — only CALLS are flagged."""
    src = """
        import time

        class Ok:
            def __init__(self, clock=time.monotonic):
                self._clock = clock

            def now(self):
                return self._clock()
    """
    assert _lint(src, select=["NX-CLOCK"]) == []


def test_clock_rule_catches_sleep_and_aliases():
    src = """
        import time as t
        from time import sleep as zzz

        class Paced:
            def __init__(self, clock=None):
                self.clock = clock

            def wait(self):
                zzz(0.1)
                t.sleep(0.2)
                return t.time()
    """
    ids = _ids(_lint(src, select=["NX-CLOCK"]))
    assert ids == ["NX-CLOCK002", "NX-CLOCK002", "NX-CLOCK001"]


def test_clock_rule_catches_datetime_now():
    src = """
        import datetime

        class Lease:
            def __init__(self, clock=None):
                self.clock = clock

            def stamp(self):
                return datetime.datetime.now(datetime.timezone.utc)
    """
    assert _ids(_lint(src, select=["NX-CLOCK"])) == ["NX-CLOCK001"]


def test_clock_rule_config_include_scopes_undetectable_modules():
    """A module with no ``clock`` parameter is still disciplined when the
    config pins it (the repo pins ha/, serving, ratelimit)."""
    cfg = LintConfig(rule_include={"NX-CLOCK": ["pinned/*.py"]})
    src = """
        import time

        def helper():
            return time.monotonic()
    """
    assert _lint(src, path="pinned/mod.py", config=cfg, select=["NX-CLOCK"])
    assert not _lint(src, path="other/mod.py", config=cfg, select=["NX-CLOCK"])


def _monotonic_cfg(scope="nexus_tpu/obs/*"):
    return LintConfig(options={"NX-CLOCK": {"monotonic_only": scope}})


def test_monotonic_only_rule_flags_wall_clock_reads():
    """NX-CLOCK003 (PR 12): in a monotonic-only zone (the obs package),
    epoch-stepping reads — time.time, datetime.now/utcnow/today — are
    banned outright; span timestamps must subtract cleanly."""
    src = """
        import time
        import datetime

        def stamp():
            return time.time()

        def stamp2():
            return datetime.datetime.utcnow()
    """
    ids = _ids(_lint(src, path="nexus_tpu/obs/mod.py",
                     config=_monotonic_cfg(), select=["NX-CLOCK003"]))
    assert ids == ["NX-CLOCK003", "NX-CLOCK003"]


def test_monotonic_only_rule_allows_monotonic_family():
    """time.monotonic()/perf_counter() ARE monotonic clocks — legal in
    the zone (they trip NX-CLOCK001 separately iff the module also
    offers clock injection, which is the discipline the obs modules
    follow by never reading clocks at all)."""
    src = """
        import time

        def stamp():
            return time.monotonic(), time.perf_counter()
    """
    assert _lint(src, path="nexus_tpu/obs/mod.py",
                 config=_monotonic_cfg(), select=["NX-CLOCK003"]) == []


def test_monotonic_only_rule_scoped_by_config_glob():
    src = """
        import time

        def stamp():
            return time.time()
    """
    assert _lint(src, path="nexus_tpu/runtime/mod.py",
                 config=_monotonic_cfg(), select=["NX-CLOCK003"]) == []
    # the repo config pins nexus_tpu/obs/* — load it and verify
    repo_cfg = load_config(os.path.join(REPO_ROOT, "nexuslint.ini"))
    assert _ids(_lint(src, path="nexus_tpu/obs/mod.py", config=repo_cfg,
                      select=["NX-CLOCK003"])) == ["NX-CLOCK003"]


def test_monotonic_only_rule_respects_suppression_comment():
    src = """
        import time

        def stamp():
            return time.time()  # nexuslint: disable=NX-CLOCK003
    """
    assert _lint(src, path="nexus_tpu/obs/mod.py",
                 config=_monotonic_cfg(), select=["NX-CLOCK003"]) == []


# ---------------------------------------------------------------------------
# NX-LOCK


LOCK_VIOLATION = """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.RLock()
            self._items = {}  # guarded-by: _lock

        def get(self, k):
            return self._items[k]
"""


def test_lock_rule_fires_on_unlocked_access():
    findings = _lint(LOCK_VIOLATION, select=["NX-LOCK"])
    assert _ids(findings) == ["NX-LOCK001"]
    assert "_items" in findings[0].message and "get()" in findings[0].message


def test_lock_rule_accepts_locked_access_and_init():
    src = """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.RLock()
                self._items = {}  # guarded-by: _lock
                self._items["seed"] = 1  # __init__ is exempt

            def get(self, k):
                with self._lock:
                    return self._items[k]
    """
    assert _lint(src, select=["NX-LOCK"]) == []


def test_lock_rule_honors_holder_method_annotation():
    src = """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.RLock()
                self._items = {}  # guarded-by: _lock

            def _bucket(self, k):  # guarded-by: _lock
                return self._items.setdefault(k, {})

            def put(self, k, v):
                with self._lock:
                    self._bucket(k)[v] = True
    """
    assert _lint(src, select=["NX-LOCK"]) == []


def test_lock_rule_flags_access_under_the_wrong_lock():
    src = """
        import threading

        class TwoLocks:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.Lock()
                self._q = []  # guarded-by: _lock

            def pop(self):
                with self._other:
                    return self._q.pop()
    """
    assert _ids(_lint(src, select=["NX-LOCK"])) == ["NX-LOCK001"]


def test_lock_rule_trailing_annotation_cannot_disable_a_method():
    """A guarded-by comment on a method's LAST line (e.g. an
    attribute-style annotation misplaced outside __init__) must not mark
    the method as a lock holder — that would silently turn NX-LOCK001
    OFF for exactly the method it was meant to tighten."""
    src = """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.RLock()
                self._items = {}  # guarded-by: _lock

            def wipe(self):
                self._items.clear()  # guarded-by: _lock
    """
    assert _ids(_lint(src, select=["NX-LOCK"])) == ["NX-LOCK001"]


def test_lock_rule_typo_guard():
    src = """
        import threading

        class Typo:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  # guarded-by: _lokc

            def pop(self):
                with self._lokc:
                    return self._q.pop()
    """
    assert "NX-LOCK002" in _ids(_lint(src, select=["NX-LOCK"]))


def test_lock_annotations_on_real_modules_are_parsed():
    """The store/informer/workqueue annotations must actually register
    (an annotation grammar drift would silently disable the rule)."""
    import ast

    from tools.nexuslint.core import FileContext
    from tools.nexuslint.rules_locks import _class_info

    expectations = {
        "nexus_tpu/cluster/store.py": ("ClusterStore", "_objects"),
        "nexus_tpu/cluster/informer.py": ("Lister", "_items"),
        "nexus_tpu/controller/workqueue.py": ("WorkQueue", "_dirty"),
    }
    for rel, (cls_name, attr) in expectations.items():
        path = os.path.join(REPO_ROOT, rel)
        ctx = FileContext(rel, open(path).read(), LintConfig())
        guarded = {}
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef) and cls.name == cls_name:
                guarded, _, _ = _class_info(ctx, cls)
        assert attr in guarded, f"{rel}: {cls_name}.{attr} lost its annotation"


# ---------------------------------------------------------------------------
# NX-JIT


JIT_VIOLATION = """
    import jax

    @jax.jit
    def step(x):
        return float(x) + x.item()
"""


def test_jit_rule_fires_on_cast_and_item():
    ids = _ids(_lint(JIT_VIOLATION, select=["NX-JIT"]))
    assert ids == ["NX-JIT002", "NX-JIT001"] or ids == ["NX-JIT001", "NX-JIT002"]


def test_jit_rule_allows_static_shape_casts():
    src = """
        import jax

        @jax.jit
        def step(x):
            n = int(x.shape[0])
            m = int(len(x.shape))
            return x * n * m
    """
    assert _lint(src, select=["NX-JIT"]) == []


def test_jit_rule_partial_decorator_and_np_random():
    src = """
        from functools import partial
        import jax
        import numpy as np

        @partial(jax.jit, static_argnums=(1,))
        def noisy(x, k):
            return x + np.random.randn(*x.shape)
    """
    assert _ids(_lint(src, select=["NX-JIT"])) == ["NX-JIT003"]


def test_jit_rule_wrapped_function_form():
    src = """
        import jax

        def step(x):
            return x.item()

        fast_step = jax.jit(step)
    """
    assert _ids(_lint(src, select=["NX-JIT"])) == ["NX-JIT001"]


def test_jit_rule_factory_form_marks_returned_workers():
    """The serving-engine idiom: ``jax.jit(make_fn(T))`` traces the
    factory's nested def, not the factory itself."""
    src = """
        import jax

        def make_chunk(width):
            scale = int(width)  # factory body is host code: legal

            def chunk(x):
                return x * x.item()  # traced body: flagged

            return chunk

        fn = jax.jit(make_chunk(8))
    """
    findings = _lint(src, select=["NX-JIT"])
    assert _ids(findings) == ["NX-JIT001"]


def test_jit_rule_mutable_default():
    src = """
        import jax

        @jax.jit
        def f(x, acc=[]):
            return x
    """
    assert _ids(_lint(src, select=["NX-JIT"])) == ["NX-JIT004"]


def test_jit_rule_ignores_plain_functions():
    src = """
        def host(x):
            return float(x) + x.item()
    """
    assert _lint(src, select=["NX-JIT"]) == []


def test_jit_rule_traces_real_serving_factories():
    """Regression probe: the engine's jitted surface must stay visible
    to the rule (a detection regression would turn NX-JIT into a no-op
    on the exact module it exists for)."""
    import ast

    from tools.nexuslint.rules_jit import _jitted_functions

    path = os.path.join(REPO_ROOT, "nexus_tpu/runtime/serving.py")
    tree = ast.parse(open(path).read())
    traced = _jitted_functions(tree)
    names = {
        n.name
        for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef) and id(n) in traced
    }
    assert {"_decode_chunk", "_insert_wave", "_spec_chunk"} <= names


# ---------------------------------------------------------------------------
# NX-PAIR


PAIR_VIOLATION = """
    def use(alloc):
        lease = alloc.admit(4)
        lease.grow_to(2)
        lease.release()
"""


def test_pair_rule_fires_without_finally():
    findings = _lint(PAIR_VIOLATION, select=["NX-PAIR"])
    assert _ids(findings) == ["NX-PAIR001", "NX-PAIR001"]  # admit + grow_to


def test_pair_rule_accepts_finally():
    src = """
        def use(alloc):
            lease = alloc.admit(4)
            try:
                lease.grow_to(2)
            finally:
                lease.release()
    """
    assert _lint(src, select=["NX-PAIR"]) == []


def test_pair_rule_accepts_context_manager_acquire():
    src = """
        def use(pool):
            with pool.acquire() as lease:
                lease.work()
            pool.release()
    """
    assert _lint(src, select=["NX-PAIR"]) == []


def test_pair_rule_skips_pure_acquire_ownership_transfer():
    src = """
        def admit_row(alloc):
            return alloc.admit(4)
    """
    assert _lint(src, select=["NX-PAIR"]) == []


def test_pair_rule_receiver_hint():
    """chaos.add:chaos.clear only matches receivers ending in `chaos` —
    a set's .add() near an unrelated .clear() must not pair up."""
    src = """
        def chaosy(server):
            server.chaos.add("error")
            run(server)
            server.chaos.clear()

        def setty(s):
            s.add(1)
            s.clear()
    """
    findings = _lint(src, select=["NX-PAIR"])
    assert _ids(findings) == ["NX-PAIR001"]
    assert findings[0].line == 3  # the chaos.add, never the set.add


def test_pair_rule_radix_insert_remove_pair():
    """index.insert:index.remove (round 9): a function that both
    publishes a digest into the radix prefix tree and prunes one must
    prune in a finally block — an exception between them strands a
    transient entry in the tree (unmatchable content holding a pool
    block). Receiver-hinted, so list.insert/list.remove on unrelated
    receivers never pair up."""
    src = """
        def speculative_publish(alloc, key, blk):
            alloc.index.insert(key, blk)
            probe(alloc)
            alloc.index.remove(blk)

        def unrelated(lst):
            lst.insert(0, 1)
            lst.remove(1)
    """
    findings = _lint(src, select=["NX-PAIR"])
    assert _ids(findings) == ["NX-PAIR001"]
    assert findings[0].line == 3  # the tree insert, never list.insert


def test_pair_rule_spill_restore_pair():
    """index.spill:index.restore (round 10, the demote/promote pair): a
    function that demotes a tree entry to the host tier and promotes it
    back must restore in a finally block — an exception between them
    leaves the entry spilled with its payload already consumed (an
    unmatchable promise the sanitizer's host-cache audit would flag at
    the next teardown). Receiver-hinted, so an unrelated .spill() or a
    checkpoint .restore() on another receiver never pairs up."""
    src = """
        def swap_through_host(alloc, key, blk):
            digest = alloc.index.spill(blk)
            stage(alloc, digest)
            alloc.index.restore(digest, blk)

        def unrelated(ckpt, bucket):
            bucket.spill()
            ckpt.restore()
    """
    findings = _lint(src, select=["NX-PAIR"])
    assert _ids(findings) == ["NX-PAIR001"]
    assert findings[0].line == 3  # the tree spill, never bucket.spill


def test_pair_rule_nested_functions_are_separate_scopes():
    src = """
        def engine(alloc):
            def admit_into(free):
                return alloc.admit(free)

            def release_row(lease):
                lease.release()

            return admit_into, release_row
    """
    assert _lint(src, select=["NX-PAIR"]) == []


# ---------------------------------------------------------------------------
# NX-IMP


IMP_VIOLATION = """
    import os
    import sys

    print(os.getcwd())
"""


def test_imp_rule_fires_on_unused():
    findings = _lint(IMP_VIOLATION, select=["NX-IMP"])
    assert _ids(findings) == ["NX-IMP001"]
    assert "sys" in findings[0].message


def test_imp_rule_carveouts():
    src = """
        import json  # noqa
        from typing import List as List
        try:
            import hypothesis
        except ImportError:
            hypothesis = None
        __all__ = ["exported"]
        from .mod import exported
    """
    assert _lint(src, select=["NX-IMP"]) == []


def test_imp_rule_skips_init_files():
    src = "import re\n"
    assert _lint(src, path="pkg/__init__.py", select=["NX-IMP"]) == []
    assert _lint(src, path="pkg/mod.py", select=["NX-IMP"])


# ---------------------------------------------------------------------------
# machinery: suppressions, config, syntax errors, CLI


def test_line_suppression():
    src = """
        import time

        class D:
            def __init__(self, clock=None):
                self.clock = clock

            def probe(self):
                return time.monotonic()  # nexuslint: disable=NX-CLOCK001
    """
    assert _lint(src, select=["NX-CLOCK"]) == []


def test_line_suppression_family_prefix_and_all():
    base = """
        import time

        class D:
            def __init__(self, clock=None):
                self.clock = clock

            def probe(self):
                return time.monotonic()  # nexuslint: disable={}
    """
    for tag in ("NX-CLOCK", "all", "NX-IMP001,NX-CLOCK001"):
        assert _lint(base.format(tag), select=["NX-CLOCK"]) == []
    # an unrelated id does NOT suppress
    assert _lint(base.format("NX-JIT001"), select=["NX-CLOCK"])


def test_file_level_suppression():
    src = """
        # nexuslint: disable-file=NX-CLOCK
        import time

        class D:
            def __init__(self, clock=None):
                self.clock = clock

            def probe(self):
                return time.monotonic()
    """
    assert _lint(src, select=["NX-CLOCK"]) == []


def test_config_rule_exclude_scoping():
    cfg = LintConfig(rule_exclude={"NX-IMP": ["tests/*"]})
    src = "import sys\n"
    assert _lint(src, path="tests/helper.py", config=cfg, select=["NX-IMP"]) == []
    assert _lint(src, path="pkg/mod.py", config=cfg, select=["NX-IMP"])


def test_syntax_error_is_a_finding():
    findings = lint_source("bad.py", "def broken(:\n")
    assert _ids(findings) == ["NX-SYNTAX"]


def test_repo_config_parses_and_scopes():
    cfg = load_config(os.path.join(REPO_ROOT, "nexuslint.ini"))
    assert "nexus_tpu/ha/lease.py" in " ".join(cfg.rule_include["NX-CLOCK"])
    assert cfg.file_excluded("__graft_entry__.py")
    assert not cfg.family_allows("NX-CLOCK", "tests/test_failover.py")
    assert cfg.family_allows("NX-CLOCK", "nexus_tpu/ha/lease.py")
    assert "admit:release" in cfg.option("NX-PAIR", "pairs")


def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import sys\nx = 1\n")
    assert nexuslint_cli.main([str(clean)]) == 0
    assert nexuslint_cli.main([str(dirty), "--select", "NX-IMP"]) == 1
    out = capsys.readouterr().out
    assert "NX-IMP001" in out
    assert nexuslint_cli.main([str(tmp_path / "missing.py")]) == 2
    assert nexuslint_cli.main(["--list-rules"]) == 0
    assert "NX-LOCK001" in capsys.readouterr().out


def test_cli_respects_quiet_and_config(tmp_path, capsys):
    dirty = tmp_path / "d.py"
    dirty.write_text("import sys\n")
    ini = tmp_path / "lint.ini"
    ini.write_text("[rule:NX-IMP]\nexclude = d.py\n")
    assert nexuslint_cli.main(
        [str(dirty), "--config", str(ini), "-q"]
    ) == 0
    assert nexuslint_cli.main(["--config", str(tmp_path / "nope.ini")]) == 2


# ---------------------------------------------------------------------------
# the gate itself: `make analyze` semantics on the repo tree


def test_repo_tree_is_clean_under_full_rule_set():
    """The exact check `make analyze` runs (nexuslint half): the tree
    must be violation-free — a rule regression OR a new violation in the
    tree fails here before it fails in CI."""
    cfg = load_config(os.path.join(REPO_ROOT, "nexuslint.ini"))
    findings = lint_paths(
        [os.path.join(REPO_ROOT, "nexus_tpu"), os.path.join(REPO_ROOT, "tools")],
        cfg,
        root=REPO_ROOT,
    )
    assert findings == [], "\n".join(f.format() for f in findings)


def test_seeded_violations_fail_each_family_end_to_end(tmp_path):
    """Acceptance drill: one seeded violation per rule family exits
    non-zero through the same path `make analyze` uses."""
    seeds = {
        "clock.py": CLOCK_VIOLATION,
        "lock.py": LOCK_VIOLATION,
        "jit.py": JIT_VIOLATION,
        "pair.py": PAIR_VIOLATION,
        "imp.py": IMP_VIOLATION,
    }
    for name, src in seeds.items():
        p = tmp_path / name
        p.write_text(textwrap.dedent(src))
        assert nexuslint_cli.main(["-q", str(p)]) == 1, name


# ---------------------------------------------------------------------------
# runtime sanitizers


def _paged_metrics(free=6, parked=0, allocated=0, reserved=0, total=6):
    return {
        "kv_layout": "paged",
        "kv_free_blocks_final": free,
        "kv_parked_blocks_final": parked,
        "kv_allocated_blocks_final": allocated,
        "kv_reserved_blocks_final": reserved,
        "kv_num_blocks": total,
    }


def test_sanitizer_pool_audit():
    from nexus_tpu.testing import sanitizers

    sanitizers.audit_pool_partition(_paged_metrics())  # clean
    sanitizers.audit_pool_partition({"kv_layout": "dense"})  # no pool: skip
    with pytest.raises(sanitizers.SanitizerError, match="leaked lease"):
        sanitizers.audit_pool_partition(_paged_metrics(free=4, allocated=2))
    with pytest.raises(sanitizers.SanitizerError, match="never refunded"):
        sanitizers.audit_pool_partition(_paged_metrics(reserved=1))
    with pytest.raises(sanitizers.SanitizerError, match="fell out"):
        sanitizers.audit_pool_partition(_paged_metrics(free=5))
    with pytest.raises(sanitizers.SanitizerError, match="missing"):
        sanitizers.audit_pool_partition({"kv_layout": "paged"})


def test_sanitizer_recompile_audit():
    from nexus_tpu.testing import sanitizers

    class Fn:
        def __init__(self, n):
            self._n = n

        def _cache_size(self):
            return self._n

    class Engine:
        pass

    eng = Engine()
    eng._decode_chunk = Fn(1)
    eng._insert_fn = Fn(2)
    counts = sanitizers.audit_recompiles(eng, bound=2)
    assert counts == {"_decode_chunk": 1, "_insert_fn": 2}
    eng._decode_chunk = Fn(37)
    with pytest.raises(sanitizers.SanitizerError, match="37 programs"):
        sanitizers.audit_recompiles(eng, bound=2)
    # narrow aliasing wide (T == 1) is counted once
    eng._decode_chunk = eng._decode_chunk_narrow = Fn(1)
    eng._insert_fn = Fn(1)
    assert "_decode_chunk_narrow" not in sanitizers.audit_recompiles(eng, bound=2)


def test_sanitizer_env_parsing(monkeypatch):
    from nexus_tpu.testing import sanitizers

    assert not sanitizers.sanitizers_enabled({})
    for off in ("0", "off", "false", "no", ""):
        assert not sanitizers.sanitizers_enabled({sanitizers.ENV_FLAG: off})
    assert sanitizers.sanitizers_enabled({sanitizers.ENV_FLAG: "1"})
    assert sanitizers.max_programs({}) == sanitizers.DEFAULT_MAX_PROGRAMS
    monkeypatch.setenv(sanitizers.ENV_MAX_PROGRAMS, "5")
    assert sanitizers.max_programs() == 5
    monkeypatch.setenv(sanitizers.ENV_MAX_PROGRAMS, "0")
    assert sanitizers.max_programs() == 1  # floor


def test_sanitizer_install_wraps_and_audits_stub_engine():
    """End to end: install → a real (cyclic-stub) paged serve passes the
    audits; a forged leaky ledger fails through the wrapper; uninstall
    restores the original serve."""
    from types import SimpleNamespace

    import jax
    import jax.numpy as jnp

    from nexus_tpu.runtime.serving import ServeRequest, ServingEngine
    from nexus_tpu.testing import sanitizers

    pre_installed = getattr(
        ServingEngine, sanitizers._INSTALLED_FLAG, False
    )
    installed = sanitizers.install()
    try:
        assert installed and sanitizers.install()  # idempotent
        v = 7
        cfg = SimpleNamespace(
            n_layers=1, n_kv_heads=1, head_dim=8, dtype=jnp.float32,
            max_seq_len=128, vocab_size=v,
        )

        def fwd(params, cfg_, tokens, cache):
            logits = jax.nn.one_hot((tokens + 1) % v, v) * 10.0
            new = {k: x for k, x in cache.items() if k != "n_valid"}
            nv = cache.get("n_valid")
            adv = tokens.shape[1] if nv is None else nv
            new["length"] = cache["length"] + adv
            return logits.astype(jnp.float32), new

        eng = ServingEngine(fwd, {}, cfg, batch_size=2, max_len=64, chunk=4)
        results, metrics = eng.serve(
            [ServeRequest(prompt=[1, 2], max_new_tokens=4)]
        )
        assert results[0].tokens[-4:] == [3, 4, 5, 6]
        assert metrics["kv_allocated_blocks_final"] == 0
        # the wrapper's own jit-program observation on a REAL engine:
        # exactly one compiled program per exercised callable
        counts = sanitizers.jit_program_counts(eng)
        assert counts["_decode_chunk"] == 1
        assert counts["_insert_fn"] == 1
    finally:
        if not pre_installed:
            # leave a conftest-installed (NEXUS_SANITIZE=1) wrap in place
            # for the rest of the session
            assert sanitizers.uninstall()
            assert not sanitizers.uninstall()  # already restored


def test_recompile_audit_fused_hydragen_one_program_on_mesh():
    """Round-8 regression probe for the fused/prefix dispatch: on the
    8-device mesh, a paged engine running the FUSED attention path with
    the Hydragen shared-prefix decomposition engaged still compiles
    exactly ONE decode and ONE insert program. The wave's shared-run
    length and aliased block ids enter the dispatch as traced OPERANDS
    (minted on the cache mesh like every other host-built array), so a
    new run length — including 0, the no-shared-run waves — is a new
    operand value, never a new compile key. A per-wave compile key here
    is precisely the regression the PR 7 sanitizer exists to catch."""
    from types import SimpleNamespace

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from nexus_tpu.runtime.serving import ServeRequest, ServingEngine
    from nexus_tpu.testing import sanitizers

    devs = jax.devices()
    assert len(devs) == 8, "conftest forces 8 host-platform devices"
    mesh = Mesh(devs, ("d",))
    v = 11
    cfg = SimpleNamespace(
        n_layers=1, n_kv_heads=1, head_dim=8, dtype=jnp.float32,
        max_seq_len=256, vocab_size=v,
    )

    def fwd(params, cfg_, tokens, cache):
        logits = jax.nn.one_hot((tokens + 1) % v, v) * 10.0
        new = {
            k: x for k, x in cache.items()
            if k not in ("n_valid", "shared_blocks", "shared_table")
        }
        nv = cache.get("n_valid")
        adv = tokens.shape[1] if nv is None else nv
        new["length"] = cache["length"] + adv
        return logits.astype(jnp.float32), new

    eng = ServingEngine(
        fwd, {}, cfg, batch_size=4, max_len=128, chunk=4,
        kv_block_size=4, prefix_cache=True, attention_path="fused",
        cache_sharding=NamedSharding(mesh, P()),
    )
    # same 12-token preamble, distinct tails: prefix-cache hits alias the
    # leading physical blocks, so decode waves carry a shared run whose
    # length varies as rows churn (plus shared-run-0 waves around them)
    preamble = [1, 2, 3, 4, 5, 6, 7, 8, 1, 2, 3, 4]
    reqs = [
        ServeRequest(prompt=preamble + [9 + (i % 2), 10], max_new_tokens=6)
        for i in range(8)
    ]
    results, metrics = eng.serve(reqs)
    assert all(len(r.tokens) > len(reqs[i].prompt)
               for i, r in enumerate(results))
    assert metrics["attention_path"] == "fused"
    assert metrics["hydragen_waves"] >= 1, (
        "the shared-preamble queue must actually engage the Hydragen "
        "decomposition for this probe to mean anything"
    )
    counts = sanitizers.jit_program_counts(eng)
    assert counts["_decode_chunk"] == 1, counts
    assert counts["_insert_fn"] == 1, counts
    # the audit's bound=1 is the steady-state contract — it must hold
    # with the fused/prefix dispatch live, shared-run lengths and all
    sanitizers.audit_recompiles(eng, bound=1)
