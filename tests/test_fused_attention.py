"""Fused block-table decode attention (ops/attention.py, round 8).

The fused kernel must be a drop-in for the gather-then-attend oracle:
same masks, same dequant, same GQA grouping — only the reduction order
differs (blockwise online softmax vs one flat softmax), so outputs agree
to f32 roundoff. These tests drive randomized block tables (permuted,
shared between rows, scratch-padded tails, STALE tails), ragged per-row
depths, GQA head ratios, sliding windows, and int8 scales against the
oracle, plus the Hydragen prefix/suffix split's exact log-sum-exp
combination. Fast lane: pure ops, no engine compiles.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nexus_tpu.ops.attention import (
    finalize_attention_partials,
    fused_paged_decode_attention,
    merge_attention_partials,
    paged_attention_partials,
    paged_decode_attention,
    shared_prefix_attention_partials,
)

TOL = dict(rtol=2e-5, atol=2e-6)


def _pool(rng, nb, bs, hkv, hd, quantized=False):
    if quantized:
        k = jnp.asarray(
            rng.randint(-127, 128, size=(nb, bs, hkv, hd)), jnp.int8
        )
        v = jnp.asarray(
            rng.randint(-127, 128, size=(nb, bs, hkv, hd)), jnp.int8
        )
        ks = jnp.asarray(
            np.abs(rng.randn(nb, bs, hkv)) * 0.02 + 1e-3, jnp.float32
        )
        vs = jnp.asarray(
            np.abs(rng.randn(nb, bs, hkv)) * 0.02 + 1e-3, jnp.float32
        )
        return k, v, ks, vs
    k = jnp.asarray(rng.randn(nb, bs, hkv, hd), jnp.float32)
    v = jnp.asarray(rng.randn(nb, bs, hkv, hd), jnp.float32)
    return k, v, None, None


def _random_table(rng, b, m, nb, share_rows=False, scratch_tails=False,
                  starts=None, bs=None):
    """Random block table over pool ids [0, nb-1); nb-1 is scratch by
    convention. ``share_rows`` aliases a common leading run across all
    rows (the prefix-cache shape); ``scratch_tails`` pads entries past
    each row's valid count with the scratch block (the allocator
    contract)."""
    ids = rng.permutation(nb - 1)[: b * m].reshape(b, m).astype(np.int32)
    if share_rows:
        ids[:, : m // 2] = ids[0, : m // 2]
    if scratch_tails:
        assert starts is not None and bs is not None
        for r in range(b):
            nblk = -(-int(starts[r] + 1) // bs)
            ids[r, nblk:] = nb - 1
    return jnp.asarray(ids)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 2)])
@pytest.mark.parametrize("window", [0, 11])
def test_fused_matches_gather_oracle_gqa_window(hq, hkv, window):
    """Randomized tables + ragged depths + GQA ratios + windows: the
    fused kernel equals the gather oracle to f32 roundoff at every
    REAL query slot."""
    rng = np.random.RandomState(hq * 31 + hkv * 7 + window)
    b, t, hd, bs, m, nb = 4, 3, 16, 8, 7, 32
    q = jnp.asarray(rng.randn(b, t, hq, hd), jnp.float32)
    k_pool, v_pool, _, _ = _pool(rng, nb, bs, hkv, hd)
    start = jnp.asarray(rng.randint(0, m * bs - t, size=b), jnp.int32)
    table = _random_table(rng, b, m, nb, share_rows=True)
    ref = paged_decode_attention(
        q, k_pool, v_pool, table, start, window=window
    )
    got = fused_paged_decode_attention(
        q, k_pool, v_pool, table, start, window=window
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), **TOL)


def test_fused_matches_gather_oracle_int8_scales():
    """int8 K/V with per-(block, position, head) scales: the per-block
    dequant is bitwise the oracle's gathered dequant."""
    rng = np.random.RandomState(5)
    b, t, hq, hkv, hd, bs, m, nb = 3, 2, 4, 2, 8, 4, 6, 24
    q = jnp.asarray(rng.randn(b, t, hq, hd), jnp.float32)
    k8, v8, ks, vs = _pool(rng, nb, bs, hkv, hd, quantized=True)
    start = jnp.asarray([1, 9, 17], jnp.int32)
    table = _random_table(rng, b, m, nb)
    ref = paged_decode_attention(
        q, k8, v8, table, start, k_scale=ks, v_scale=vs
    )
    got = fused_paged_decode_attention(
        q, k8, v8, table, start, k_scale=ks, v_scale=vs
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), **TOL)


def test_stale_table_tail_entries_are_never_read():
    """The valid-block mask + scratch redirect make the output BITWISE
    independent of what unmapped tail entries point at — in-range
    aliases of other rows' blocks and out-of-range garbage alike (the
    tightened gather_kv_blocks contract, enforced kernel-side)."""
    rng = np.random.RandomState(9)
    b, t, hq, hkv, hd, bs, m, nb = 3, 2, 4, 2, 8, 8, 8, 32
    q = jnp.asarray(rng.randn(b, t, hq, hd), jnp.float32)
    k_pool, v_pool, _, _ = _pool(rng, nb, bs, hkv, hd)
    starts = np.asarray([3, 20, 33])
    clean = np.asarray(_random_table(
        rng, b, m, nb, scratch_tails=True, starts=starts, bs=bs
    ))
    stale = clean.copy()
    for r in range(b):
        nblk = -(-int(starts[r] + t) // bs)
        # stale tails: other rows' live blocks AND out-of-range ids
        stale[r, nblk:] = rng.randint(0, nb + 50, size=m - nblk)
    start = jnp.asarray(starts, jnp.int32)
    out_clean = np.asarray(fused_paged_decode_attention(
        q, k_pool, v_pool, jnp.asarray(clean), start
    ))
    out_stale = np.asarray(fused_paged_decode_attention(
        q, k_pool, v_pool, jnp.asarray(stale), start
    ))
    np.testing.assert_array_equal(out_clean, out_stale)


def test_lse_merge_of_prefix_suffix_split_is_exact():
    """The Hydragen combination rule: partials over slots [0, s) and
    [s, hi) merged via log-sum-exp equal the unsplit loop bitwise-close
    and the oracle to roundoff — at EVERY split point, including the
    degenerate s=0 and s=hi ends."""
    rng = np.random.RandomState(13)
    b, t, hq, hkv, hd, bs, m, nb = 3, 2, 4, 2, 8, 4, 6, 20
    q = jnp.asarray(rng.randn(b, t, hq, hd), jnp.float32)
    k_pool, v_pool, _, _ = _pool(rng, nb, bs, hkv, hd)
    start = jnp.asarray([7, 15, 22], jnp.int32)
    table = _random_table(rng, b, m, nb, share_rows=True)
    n_blocks = jnp.clip(-(-(start + t) // bs), 1, m)
    hi = jnp.max(n_blocks)
    ref = np.asarray(paged_decode_attention(q, k_pool, v_pool, table, start))
    full = paged_attention_partials(
        q, k_pool, v_pool, table, start, 0, hi, n_blocks
    )
    out_full = np.asarray(finalize_attention_partials(full, q.dtype))
    for s in range(int(hi) + 1):
        s_ = jnp.int32(s)
        prefix = paged_attention_partials(
            q, k_pool, v_pool, table, start, 0, s_, n_blocks
        )
        suffix = paged_attention_partials(
            q, k_pool, v_pool, table, start, s_, hi, n_blocks
        )
        merged = merge_attention_partials(prefix, suffix)
        out = np.asarray(finalize_attention_partials(merged, q.dtype))
        np.testing.assert_allclose(out, out_full, **TOL)
        np.testing.assert_allclose(out, ref, **TOL)


def test_hydragen_shared_prefix_decomposition_matches_oracle():
    """Rows aliasing the same leading blocks: the batched-queries prefix
    partials (each shared block read ONCE) + per-row suffix + LSE merge
    equal the oracle; shared_blocks=0 equals the plain fused loop in the
    same code path (the no-shared-run fall-through)."""
    rng = np.random.RandomState(21)
    b, t, hq, hkv, hd, bs, m, nb = 4, 2, 4, 2, 8, 4, 6, 32
    q = jnp.asarray(rng.randn(b, t, hq, hd), jnp.float32)
    for quant in (False, True):
        k_pool, v_pool, ks, vs = _pool(rng, nb, bs, hkv, hd, quant)
        table = _random_table(rng, b, m, nb, share_rows=True)
        # every row deep enough to cover the shared run (m//2 blocks)
        start = jnp.asarray(rng.randint(m // 2 * bs, m * bs - t, size=b),
                            jnp.int32)
        kw = dict(k_scale=ks, v_scale=vs) if quant else {}
        ref = paged_decode_attention(q, k_pool, v_pool, table, start, **kw)
        got = fused_paged_decode_attention(
            q, k_pool, v_pool, table, start,
            shared_blocks=jnp.int32(m // 2), shared_table=table[0], **kw,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), err_msg=f"quant={quant}",
            **TOL,
        )
        plain = fused_paged_decode_attention(
            q, k_pool, v_pool, table, start, **kw
        )
        zero = fused_paged_decode_attention(
            q, k_pool, v_pool, table, start,
            shared_blocks=jnp.int32(0), shared_table=table[0], **kw,
        )
        np.testing.assert_array_equal(np.asarray(zero), np.asarray(plain))


def test_shared_prefix_partials_mask_shallow_rows():
    """A row whose depth does NOT reach into the shared run (its q_pos
    sits below some shared positions) sees those positions masked in
    the prefix partials exactly as the per-row loop would — the split
    never leaks future keys into a shallow row."""
    rng = np.random.RandomState(3)
    b, t, hq, hkv, hd, bs, m, nb = 3, 1, 4, 2, 8, 4, 4, 16
    q = jnp.asarray(rng.randn(b, t, hq, hd), jnp.float32)
    k_pool, v_pool, _, _ = _pool(rng, nb, bs, hkv, hd)
    table = _random_table(rng, b, m, nb, share_rows=True)
    # row 1's depth ends INSIDE shared block 1; row 2 before block 1
    start = jnp.asarray([2 * bs + 1, bs + 1, 2], jnp.int32)
    n_blocks = jnp.clip(-(-(start + t) // bs), 1, m)
    prefix = shared_prefix_attention_partials(
        q, k_pool, v_pool, table[0], jnp.int32(2), start, n_blocks
    )
    per_row = paged_attention_partials(
        q, k_pool, v_pool, table, start, 0, jnp.int32(2), n_blocks
    )
    for a, bb in zip(prefix, per_row):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-6, atol=1e-7
        )


def test_fused_under_jit_with_traced_operands():
    """shared_blocks / n_blocks / start are VALUES, not compile keys:
    one jitted wrapper serves every run length and depth (the engine's
    one-compiled-program contract rides exactly this)."""
    rng = np.random.RandomState(17)
    b, t, hq, hkv, hd, bs, m, nb = 3, 2, 4, 2, 8, 4, 6, 24
    q = jnp.asarray(rng.randn(b, t, hq, hd), jnp.float32)
    k_pool, v_pool, _, _ = _pool(rng, nb, bs, hkv, hd)
    table = _random_table(rng, b, m, nb, share_rows=True)

    @jax.jit
    def run(start, sb):
        return fused_paged_decode_attention(
            q, k_pool, v_pool, table, start,
            shared_blocks=sb, shared_table=table[0],
        )

    for depth, sb in ((13, 0), (17, 1), (22, 3)):
        start = jnp.asarray([depth, depth + 1, depth + 2], jnp.int32)
        ref = paged_decode_attention(q, k_pool, v_pool, table, start)
        got = run(start, jnp.int32(sb))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), err_msg=f"sb={sb}", **TOL
        )
    assert run._cache_size() == 1


@pytest.mark.parametrize("quantized", [False, True])
def test_verify_window_multi_query_causal_through_table(quantized):
    """The round-11 speculation contract on the kernel itself: a k+1
    VERIFY WINDOW of queries (each at its own causal position — query j
    of the window sees positions <= start+j, INCLUDING the window's own
    earlier K/V slots, which speculation writes before it scores)
    attends through the block table identically on the fused path, the
    gather oracle, and with the Hydragen split live — across fp and
    int8 pools and a sliding window. This is the single program the
    serve engine dispatches once per speculation round."""
    rng = np.random.RandomState(41)
    k = 4  # num_speculative; the verify window is k+1 wide
    b, hq, hkv, hd, bs, m, nb = 3, 4, 2, 8, 4, 8, 32
    t = k + 1
    q = jnp.asarray(rng.randn(b, t, hq, hd), jnp.float32)
    k_pool, v_pool, ks, vs = _pool(rng, nb, bs, hkv, hd,
                                   quantized=quantized)
    # per-row depths land the window at arbitrary block offsets,
    # including straddling a block boundary mid-window
    start = jnp.asarray([5, 11, 18], jnp.int32)
    table = _random_table(rng, b, m, nb, share_rows=True)
    for window in (0, 9):
        ref = paged_decode_attention(
            q, k_pool, v_pool, table, start, window=window,
            k_scale=ks, v_scale=vs,
        )
        got = fused_paged_decode_attention(
            q, k_pool, v_pool, table, start, window=window,
            k_scale=ks, v_scale=vs,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref),
            err_msg=f"window={window}", **TOL
        )
        hyd = fused_paged_decode_attention(
            q, k_pool, v_pool, table, start, window=window,
            k_scale=ks, v_scale=vs,
            shared_blocks=jnp.int32(2), shared_table=table[0],
        )
        np.testing.assert_allclose(
            np.asarray(hyd), np.asarray(ref),
            err_msg=f"hydragen window={window}", **TOL
        )
    # in-window causality: zeroing K/V at positions ABOVE each row's
    # window must not change the output (nothing there is visible even
    # to the window's newest query). Rows get DISJOINT tables here — a
    # shared block's tail can be another (deeper) row's visible middle.
    table = _random_table(rng, b, m, nb, share_rows=False)
    hi_pos = np.asarray(start) + t  # first invisible position per row
    k_mut, v_mut = np.asarray(k_pool).copy(), np.asarray(v_pool).copy()
    tbl = np.asarray(table)
    for r in range(b):
        for slot in range(m):
            blk = int(tbl[r, slot])
            for off in range(bs):
                if slot * bs + off >= hi_pos[r]:
                    k_mut[blk, off] = 0
                    v_mut[blk, off] = 0
    got2 = fused_paged_decode_attention(
        q, jnp.asarray(k_mut, k_pool.dtype), jnp.asarray(v_mut, v_pool.dtype),
        table, start, k_scale=ks, v_scale=vs,
    )
    base = fused_paged_decode_attention(
        q, k_pool, v_pool, table, start, k_scale=ks, v_scale=vs,
    )
    np.testing.assert_allclose(np.asarray(got2), np.asarray(base), **TOL)
