"""Tiered KV cache (round 10): the host-RAM spill tier.

Fast tier (`make spill-smoke`, sanitizer-armed): the HostBlockStore,
the radix tree's SPILLED residency state, and the allocator's
demote/promote protocol are pure host code, and the engine lane runs
the cyclic stub model — so evict→spill→re-match→restore executes in
seconds on CPU on every dev-lane run. The llama-backed numeric
exactness tiers (host tier on == off == cache off, across fused/gather
× fp/int8 pools) live in tests/test_serving.py with the rest of the
compile-bound contract.

Property coverage (hypothesis front-end + an unconditional seeded
fallback, the repo's usual pair): random admit/grow/register/release/
spill/restore sequences assert after EVERY operation that

  * free / parked / referenced partition the POOL exactly while the
    spilled set lives outside it — resident ∪ spilled entries are the
    matchable cache, and no spilled entry ever holds (or is held by) a
    pool block;
  * the host store's digests equal the tree's spilled markers bit for
    bit, with exact byte accounting (the sanitizer's coherence audit);
  * every restore is BYTE-IDENTICAL to the payload that was spilled for
    a "native" store, and within the quantizer's documented error
    (|err| <= max|vec|/254 per element) for int8 demotion.
"""

import numpy as np
import pytest
from types import SimpleNamespace

import jax
import jax.numpy as jnp

from nexus_tpu.runtime.host_cache import (
    HostBlockStore,
    dequantize_kv_host,
    quantize_kv_host,
)
from nexus_tpu.runtime.prefix_cache import (
    SPILLED,
    PrefixCacheIndex,
    chain_keys,
)
from nexus_tpu.runtime.serving import (
    BlockAllocator,
    ServeRequest,
    ServingEngine,
)

try:
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:
    HAVE_HYPOTHESIS = False

NUM_BLOCKS = 10
BLOCK_SIZE = 4


# --------------------------------------------------------------- store


def _planes(rng, scale=1.0):
    return {
        "k": (rng.randn(2, BLOCK_SIZE, 1, 8) * scale).astype(np.float32),
        "v": (rng.randn(2, BLOCK_SIZE, 1, 8) * scale).astype(np.float32),
    }


def test_store_put_take_bytes_roundtrip():
    rng = np.random.RandomState(0)
    store = HostBlockStore(1 << 20)
    p1, p2 = _planes(rng), _planes(rng)
    store.put(b"a", p1)
    store.put(b"b", p2)
    assert len(store) == 2 and b"a" in store
    assert store.bytes == sum(a.nbytes for a in p1.values()) * 2
    assert store.bytes_peak == store.bytes
    store.audit()
    got, demoted = store.take(b"a")
    assert not demoted
    for key in ("k", "v"):
        assert np.array_equal(got[key], p1[key])  # byte-identical
    assert len(store) == 1
    store.drop(b"b")
    assert store.bytes == 0 and len(store) == 0
    store.audit()
    with pytest.raises(KeyError):
        store.take(b"a")  # already promoted
    store.put(b"a", p1)
    with pytest.raises(ValueError):
        store.put(b"a", p1)  # one entry per digest
    # stats(): the round-12 ledger/tooling snapshot mirrors the
    # attributes exactly (the engine's host-cache metrics read it)
    assert store.stats() == {
        "entries": 1,
        "bytes": store.bytes,
        "bytes_peak": store.bytes_peak,
        "budget_bytes": 1 << 20,
        "puts": store.puts,
        "takes": store.takes,
        "drops": store.drops,
    }
    with pytest.raises(ValueError):
        HostBlockStore(-1)
    with pytest.raises(ValueError):
        HostBlockStore(0, dtype="fp4")


def test_store_int8_demotion_error_bound():
    """int8 demotion quantizes per (layer, position, head) vector at
    max-abs/127 — the restore must land within half a step
    (max|vec|/254) of the original, the same documented error model as
    the device int8 cache; and an ALREADY-int8 payload (a quantized
    pool's block) passes through byte-identical."""
    rng = np.random.RandomState(1)
    store = HostBlockStore(1 << 20, dtype="int8")
    orig = _planes(rng, scale=3.0)
    store.put(b"x", orig)
    assert store.bytes < sum(a.nbytes for a in orig.values())  # smaller
    got, demoted = store.take(b"x")
    assert demoted
    for key in ("k", "v"):
        deq = dequantize_kv_host(got[key], got[key + "_scale"])
        bound = (
            np.abs(orig[key]).max(axis=-1, keepdims=True) / 254.0 + 1e-6
        )
        assert (np.abs(deq - orig[key]) <= bound).all()
    # int8-pool payloads: nothing to demote, byte-identical
    qk, ks = quantize_kv_host(orig["k"])
    qv, vs = quantize_kv_host(orig["v"])
    quant = {"k": qk, "v": qv, "k_scale": ks, "v_scale": vs}
    store.put(b"q", quant)
    got, demoted = store.take(b"q")
    assert not demoted
    for key in quant:
        assert np.array_equal(got[key], quant[key])


def test_quantize_host_zero_vector_is_safe():
    q, s = quantize_kv_host(np.zeros((1, 2, 1, 8), np.float32))
    assert (q == 0).all() and (s == 0).all()
    assert (dequantize_kv_host(q, s) == 0).all()


# ------------------------------------------------------ index spill ops


def _chain_index(n=4):
    idx = PrefixCacheIndex()
    keys = chain_keys(list(range(n * BLOCK_SIZE)), BLOCK_SIZE)
    for j, k in enumerate(keys):
        assert idx.insert(k, j, parent=keys[j - 1] if j else None)
    return idx, keys


def test_index_spill_keeps_chain_matchable_and_restores():
    idx, keys = _chain_index(4)
    for b in (0, 1, 2, 3):
        idx.park(b)
    # leaf-first: spilling the tail, then the next-exposed tail
    blk, key = idx.spill_lru()
    assert (blk, key) == (3, keys[3])
    blk, key = idx.spill_lru()
    assert (blk, key) == (2, keys[2])
    idx.audit()
    # the resident match stops at the spilled frontier; the tiered
    # match reports the restorable continuation
    assert idx.match(keys) == [0, 1]
    assert idx.match_tiered(keys) == ([0, 1], [keys[2], keys[3]])
    assert idx.holder(keys[2]) is None  # spilled content is nobody's
    assert idx.spilled_count == 2
    # restore the frontier entry into a fresh block: resident again,
    # referenced (not parked), deeper entry still spilled. The
    # restoring admission maps the resident prefix SHARED first (the
    # allocator bumps refcounts → unpark), so mirror that here — a
    # referenced entry under parked ancestors would rightly fail the
    # closure audit
    idx.unpark(0)
    idx.unpark(1)
    idx.restore(keys[2], 7)
    idx.audit()
    assert idx.match_tiered(keys) == ([0, 1, 7], [keys[3]])
    assert idx.holder(keys[2]) == 7
    with pytest.raises(ValueError):
        idx.restore(keys[2], 8)  # not spilled anymore
    with pytest.raises(ValueError):
        idx.restore(keys[3], 7)  # block 7 already holds content


def test_index_spill_refuses_resident_descendants():
    idx, keys = _chain_index(3)
    for b in (0, 1, 2):
        idx.park(b)
    with pytest.raises(RuntimeError):
        idx.spill(0)  # interior entry with resident descendants
    # but once the tail is spilled, its predecessor becomes spillable
    assert idx.spill(2) == keys[2]
    assert idx.spill(1) == keys[1]
    idx.audit()


def test_index_spilled_insert_refused_first_writer_wins():
    """A spilled digest still OWNS its key: a row that re-prefilled the
    same content cannot re-register it (the spilled entry would be
    shadowed and the store entry stranded) — exactly the engine's
    first-writer-wins rule extended to the host tier."""
    idx, keys = _chain_index(2)
    idx.park(0)
    idx.park(1)
    idx.spill_lru()  # spills block 1 / keys[1]
    assert idx.insert(keys[1], 9, parent=keys[0]) is False
    idx.audit()


def test_index_evict_spilled_lru_is_leaf_first():
    idx, keys = _chain_index(4)
    for b in (0, 1, 2, 3):
        idx.park(b)
    for _ in range(4):
        idx.spill_lru()  # whole chain demoted, tail-first
    idx.audit()
    assert idx.spilled_count == 4
    # host-budget eviction drops full leaves, deepest spilled first —
    # LRU order IS leaf-first because spill stamped tails earlier
    assert idx.evict_spilled_lru() == keys[3]
    assert idx.evict_spilled_lru() == keys[2]
    idx.audit()
    assert idx.match_tiered(keys) == ([], [keys[0], keys[1]])
    assert idx.evict_spilled_lru() == keys[1]
    assert idx.evict_spilled_lru() == keys[0]
    with pytest.raises(RuntimeError):
        idx.evict_spilled_lru()
    idx.audit()
    assert len(idx) == 0


def test_index_interior_spill_then_host_eviction_rearms():
    """Spill an interior entry (its run-tail descendants already
    spilled), drop the descendants under host pressure, and the
    interior entry must become the droppable frontier — the lazy-heap
    re-arm `_remove_entry` performs on exposure."""
    idx, keys = _chain_index(3)
    for b in (0, 1, 2):
        idx.park(b)
    idx.spill_lru()  # 2
    idx.spill_lru()  # 1 (interior at spill time: child 2 is spilled)
    assert idx.evict_spilled_lru() == keys[2]  # the full leaf first
    idx.audit()
    assert idx.evict_spilled_lru() == keys[1]  # re-armed on exposure
    idx.audit()


# ------------------------------------------------- allocator spill tier


def _fake_spill_env(num_blocks=NUM_BLOCKS, budget=1 << 20,
                    dtype="native"):
    """Allocator + store wired with a DETERMINISTIC per-digest payload
    generator (content derives from the digest), plus the oracle map of
    what was spilled — restores are checked against it bit for bit."""
    store = HostBlockStore(budget, dtype=dtype)
    idx = PrefixCacheIndex()
    alloc = BlockAllocator(
        num_blocks, BLOCK_SIZE, prefix_index=idx, host_cache=store
    )
    oracle = {}

    def spill_fn(blk, key):
        rng = np.random.RandomState(
            int.from_bytes(key[:4], "big") % (2**31 - 1)
        )
        planes = _planes(rng)
        oracle[key] = {k: v.copy() for k, v in planes.items()}
        return planes

    alloc.spill_fn = spill_fn
    return alloc, idx, store, oracle


def _assert_restore_fidelity(lease, oracle, dtype):
    """Every restored payload must reproduce what was spilled: checked
    by content identity against the oracle of downloaded planes."""
    for blk, payload, demoted in lease.restored_payloads:
        # find the oracle entry this payload came from: demoted
        # payloads dequantize within the documented bound; native ones
        # are byte-identical to exactly one oracle entry
        if not demoted:
            assert any(
                np.array_equal(payload["k"], o["k"])
                and np.array_equal(payload["v"], o["v"])
                for o in oracle.values()
            ), "native restore is not byte-identical to any spill"
        else:
            deq = {
                "k": dequantize_kv_host(
                    payload["k"], payload["k_scale"]
                ),
                "v": dequantize_kv_host(
                    payload["v"], payload["v_scale"]
                ),
            }
            def within(o):
                for k in ("k", "v"):
                    bound = (
                        np.abs(o[k]).max(axis=-1, keepdims=True) / 254.0
                        + 1e-6
                    )
                    if not (np.abs(deq[k] - o[k]) <= bound).all():
                        return False
                return True
            assert any(within(o) for o in oracle.values()), (
                "int8 restore exceeds the documented quantizer error"
            )


def test_allocator_pressure_spills_then_restores_exactly():
    alloc, idx, store, oracle = _fake_spill_env()
    keys = chain_keys(list(range(4 * BLOCK_SIZE)), BLOCK_SIZE)
    l1 = alloc.admit(4)
    blks = l1.grow_to(4)
    for j, (k, b) in enumerate(zip(keys, blks)):
        alloc.register_block(k, b, parent=keys[j - 1] if j else None)
    l1.release()
    assert alloc.cached_blocks == 4
    # pressure: a 10-block admission drains free (6) then spills the 4
    # parked blocks — demoted, not destroyed
    l2 = alloc.admit(10)
    l2.grow_to(10)
    assert alloc.spills == 4 and alloc.evictions == 4
    assert idx.spilled_count == 4 and len(store) == 4
    assert set(store.keys()) == set(idx._spilled)
    idx.audit()
    store.audit()
    l2.release()
    # the chain re-matches THROUGH the host tier and restores: the cap
    # at p-1 drops the last spilled block (re-prefilled instead)
    shared, skeys, matched, cow = alloc.match_prefix(
        keys, 4 * BLOCK_SIZE
    )
    assert shared == [] and skeys == keys[:3] and cow is None
    assert matched == 3 * BLOCK_SIZE
    l3 = alloc.admit(2, restore=skeys)
    assert l3 is not None and alloc.restores == 3
    assert [k for k, _ in zip(keys, l3.shared)] == keys[:3]
    assert len(l3.restored_payloads) == 3
    _assert_restore_fidelity(l3, oracle, "native")
    assert idx.spilled_count == 1 and len(store) == 1
    idx.audit()
    store.audit()
    restored = list(l3.shared)
    l3.release()
    # restored blocks park again at release — matchable as plain
    # RESIDENT content now, no host tier needed
    assert alloc.match_prefix(keys, 4 * BLOCK_SIZE)[0] == restored


def test_allocator_host_budget_eviction_keeps_coherence():
    """A budget that fits only ~2 blocks: spilling 4 drains the excess
    leaf-first, and tree/store stay in lockstep throughout."""
    rng = np.random.RandomState(3)
    one_block = sum(a.nbytes for a in _planes(rng).values())
    alloc, idx, store, oracle = _fake_spill_env(
        budget=2 * one_block
    )
    keys = chain_keys(list(range(4 * BLOCK_SIZE)), BLOCK_SIZE)
    l1 = alloc.admit(4)
    blks = l1.grow_to(4)
    for j, (k, b) in enumerate(zip(keys, blks)):
        alloc.register_block(k, b, parent=keys[j - 1] if j else None)
    l1.release()
    l2 = alloc.admit(10)
    l2.grow_to(10)
    assert alloc.spills == 4
    assert alloc.host_evictions == 2  # drained back to the budget
    assert len(store) == 2 and idx.spilled_count == 2
    assert set(store.keys()) == set(idx._spilled)
    # the SHALLOW half of the chain survived (leaf-first drop), so the
    # prefix stays restorable
    assert set(store.keys()) == set(keys[:2])
    assert not store.over_budget()
    idx.audit()
    store.audit()


def test_allocator_admission_gate_counts_restores():
    alloc, idx, store, oracle = _fake_spill_env(num_blocks=4)
    keys = chain_keys(list(range(3 * BLOCK_SIZE)), BLOCK_SIZE)
    l1 = alloc.admit(3)
    blks = l1.grow_to(3)
    for j, (k, b) in enumerate(zip(keys, blks)):
        alloc.register_block(k, b, parent=keys[j - 1] if j else None)
    l1.release()
    l2 = alloc.admit(4)
    l2.grow_to(4)  # spills all 3
    assert idx.spilled_count == 3
    # restoring 2 + reserving 3 privates needs 5 > 4: refused, nothing
    # mutated (the spilled set is untouched by a refused admission)
    _, skeys, _, _ = alloc.match_prefix(keys, 3 * BLOCK_SIZE)
    l2.release()
    assert alloc.admit(3, restore=skeys[:2]) is None
    assert idx.spilled_count == 3 and len(store) == 3
    idx.audit()
    store.audit()
    lease = alloc.admit(2, restore=skeys[:2])
    assert lease is not None
    assert idx.spilled_count == 1
    lease.release()


# --------------------------------------------------- property drivers


def _chains():
    chains = []
    for i in range(3):
        toks = [(7 * i + t) % 50 for t in range(5 * BLOCK_SIZE)]
        chains.append(
            (toks, chain_keys(toks, BLOCK_SIZE))
        )
    return chains


def _check_tiered(alloc, idx, store, leases):
    refs = [0] * NUM_BLOCKS
    for lease, _c, _cov in leases:
        for blk in lease.blocks:
            refs[blk] += 1
    assert refs == alloc._ref, (refs, alloc._ref)
    free = set(alloc._free)
    parked = set(idx._parked)
    referenced = {b for b in range(NUM_BLOCKS) if refs[b] > 0}
    # free / parked / referenced partition the POOL exactly; spilled
    # entries live OUTSIDE it (no pool block) — resident ∪ spilled is
    # the matchable cache
    assert not (free & parked)
    assert not (free & referenced)
    assert not (parked & referenced)
    assert free | parked | referenced == set(range(NUM_BLOCKS))
    # spilled entries are never referenced (they have no block at all):
    # every spilled digest maps to the SPILLED sentinel in its run
    for key in idx._spilled:
        node, off = idx._by_key[key]
        assert node.blocks[off] == SPILLED
    # host store ⟺ tree, bit for bit, with exact byte accounting
    assert set(store.keys()) == set(idx._spilled)
    assert len(free) + len(parked) >= alloc._reserved >= 0
    idx.audit()
    store.audit()


def _drive_tiered(ops, dtype, budget=1 << 20):
    alloc, idx, store, oracle = _fake_spill_env(
        budget=budget, dtype=dtype
    )
    chains = _chains()
    leases = []  # (lease, chain idx, chain keys covered)

    for kind, x, y in ops:
        if kind == 0:  # admit a chain, reusing resident + spilled spans
            toks, keys = chains[x % len(chains)]
            shared, skeys, matched, cow = alloc.match_prefix(
                keys, len(toks) + 3  # +3: partial tail, cap never hits
            )
            assert cow is None
            need = y % 5
            lease = alloc.admit(need, shared=shared, restore=skeys)
            if lease is not None:
                _assert_restore_fidelity(lease, oracle, dtype)
                leases.append(
                    (lease, x % len(chains),
                     len(shared) + len(skeys))
                )
        elif kind == 1 and leases:  # grow within the reservation
            lease, _c, _cov = leases[x % len(leases)]
            lease.grow_to(y % (NUM_BLOCKS + 2))
        elif kind == 2 and leases:  # release
            lease, _c, _cov = leases.pop(x % len(leases))
            lease.release()
        elif kind == 3 and leases:  # publish the next chain block
            i = x % len(leases)
            lease, c, cov = leases[i]
            _toks, keys = chains[c]
            unreg = [
                b for b in lease._private if not idx.holds(b)
            ]
            if cov < len(keys) and unreg:
                # the engine's registration guard: extend only under a
                # parent digest held by this lease's own block
                if cov == 0 or (
                    cov - 1 < len(lease.blocks)
                    and idx.holder(keys[cov - 1])
                    == lease.blocks[cov - 1]
                ):
                    if alloc.register_block(
                        keys[cov], unreg[0],
                        parent=keys[cov - 1] if cov else None,
                    ):
                        leases[i] = (lease, c, cov + 1)
        _check_tiered(alloc, idx, store, leases)

    for lease, _c, _cov in leases:
        lease.release()
    leases = []
    _check_tiered(alloc, idx, store, leases)


if HAVE_HYPOTHESIS:
    _op = st.tuples(
        st.integers(0, 3), st.integers(0, 31), st.integers(0, 31)
    )

    @settings(
        max_examples=80, deadline=None,
        suppress_health_check=[HealthCheck.too_slow],
    )
    @given(
        ops=st.lists(_op, max_size=50),
        dtype=st.sampled_from(["native", "int8"]),
    )
    def test_tiered_allocator_property(ops, dtype):
        _drive_tiered(ops, dtype)


def test_tiered_allocator_property_random_driver():
    """The no-hypothesis fallback: seeded random admit/grow/register/
    release sequences (spills and restores arise from pool pressure)
    through the same driver, both host dtypes — partition exactness,
    store/tree lockstep, and restore fidelity on every tier-1 run."""
    rng = np.random.RandomState(20260803)
    for trial in range(200):
        n = int(rng.randint(0, 45))
        ops = [
            (int(rng.randint(0, 4)), int(rng.randint(0, 32)),
             int(rng.randint(0, 32)))
            for _ in range(n)
        ]
        _drive_tiered(ops, "native" if trial % 2 else "int8")


def test_tiered_allocator_property_tiny_host_budget():
    """Same driver under a budget of ~1.5 blocks: host evictions fire
    constantly and coherence must survive them."""
    rng = np.random.RandomState(4242)
    one_block = sum(a.nbytes for a in _planes(rng).values())
    for trial in range(60):
        n = int(rng.randint(5, 40))
        ops = [
            (int(rng.randint(0, 4)), int(rng.randint(0, 32)),
             int(rng.randint(0, 32)))
            for _ in range(n)
        ]
        _drive_tiered(
            ops, "native", budget=one_block + one_block // 2
        )


# ------------------------------------------------------- engine lane


def _cyclic_model(v: int):
    """next = (token + 1) % v — deterministic, no K/V reads (spill
    SCHEDULING is under test here; the real K/V roundtrip through the
    pool is covered by test_serving.py's llama tiers)."""
    cfg = SimpleNamespace(
        n_layers=1, n_kv_heads=1, head_dim=8, dtype=jnp.float32,
        max_seq_len=256, vocab_size=v,
    )

    def fwd(params, cfg_, tokens, cache):
        logits = jax.nn.one_hot((tokens + 1) % v, v) * 10.0
        new = {k: x for k, x in cache.items() if k != "n_valid"}
        nv = cache.get("n_valid")
        adv = tokens.shape[1] if nv is None else nv
        new["length"] = cache["length"] + adv
        return logits.astype(jnp.float32), new

    return cfg, fwd


def _expect(req, v):
    out, cur = [], req.prompt[-1]
    for _ in range(req.max_new_tokens):
        cur = (cur + 1) % v
        out.append(cur)
    return list(req.prompt) + out


def _pressure_queue(v, rng, groups=2, repeats=3):
    """Alternating warm prompt families through a pool too small to
    keep both resident — the workload where the pre-round-10 allocator
    recomputed every re-admission from scratch."""
    fams = [rng.randint(0, v, size=16).tolist() for _ in range(groups)]
    reqs = []
    for r in range(repeats):
        for g in fams:
            reqs.append(ServeRequest(
                prompt=g + rng.randint(0, v, size=4).tolist(),
                max_new_tokens=4,
            ))
    return reqs


def test_engine_spill_restore_roundtrip_under_pressure():
    """The spill-smoke headline: a 4-block pool serving two alternating
    16-token warm families (FIFO, so reordering can't dodge the
    pressure). Host tier OFF: every re-admission is a full recompute —
    zero hit tokens. Host tier ON: evictions demote, re-admissions
    restore — hit tokens > 0 with restore_hit_tokens > 0, prefill
    steps strictly below the off-baseline, outputs identical, and the
    armed sanitizers (pool partition + radix + host-cache coherence)
    pass at teardown."""
    v = 13
    cfg, fwd = _cyclic_model(v)
    reqs = _pressure_queue(v, np.random.RandomState(5))
    metrics, outs = {}, {}
    for host_bytes in (0, 1 << 20):
        eng = ServingEngine(
            fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
            kv_block_size=8, kv_num_blocks=4, prefix_cache=True,
            admission_policy="fifo", host_cache_bytes=host_bytes,
        )
        eng._sanitize = True  # per-wave audits armed regardless of env
        results, m = eng.serve(reqs)
        for req, res in zip(reqs, results):
            assert res.tokens == _expect(req, v), host_bytes
        metrics[host_bytes], outs[host_bytes] = m, [
            r.tokens for r in results
        ]
    assert outs[0] == outs[1 << 20]
    off, on = metrics[0], metrics[1 << 20]
    assert off.get("prefix_hit_tokens", 0) == 0  # warm prompts LOST
    assert on["host_cache_enabled"] is True
    assert on["spilled_blocks"] > 0
    assert on["restored_blocks"] > 0
    assert on["restore_hit_tokens"] > 0
    assert on["prefix_hit_tokens"] >= on["restore_hit_tokens"]
    assert on["prefill_steps"] < off["prefill_steps"]
    assert on["host_cache_bytes_peak"] > 0
    # spilled tier accounts 1:1 at teardown (the sanitizer's partition)
    assert (on["kv_spilled_blocks_final"]
            == on["host_cache_entries_final"])


def test_engine_int8_pool_and_int8_demotion_stay_exact_on_stub():
    """kvPoolDtype='int8' × hostCacheDtype sweeps on the stub engine:
    spill/restore scheduling is identical across dtypes and the
    int8-pool spill payload restores byte-identically (asserted inside
    the allocator property above; here the end-to-end serve ledger)."""
    v = 11
    cfg, fwd = _cyclic_model(v)
    reqs = _pressure_queue(v, np.random.RandomState(9))
    base = None
    for pool_dtype in ("native", "int8"):
        for host_dtype in ("native", "int8"):
            eng = ServingEngine(
                fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
                kv_block_size=8, kv_num_blocks=4, prefix_cache=True,
                admission_policy="fifo", host_cache_bytes=1 << 20,
                kv_pool_dtype=pool_dtype, host_cache_dtype=host_dtype,
            )
            eng._sanitize = True
            results, m = eng.serve(reqs)
            toks = [r.tokens for r in results]
            for req, res in zip(reqs, results):
                assert res.tokens == _expect(req, v)
            base = base or toks
            assert toks == base
            assert m["restore_hit_tokens"] > 0
            assert m["host_cache_dtype"] == host_dtype
    with pytest.raises(ValueError):
        ServingEngine(fwd, {}, cfg, batch_size=1, max_len=96,
                      kv_pool_dtype="fp4")
    with pytest.raises(ValueError):
        ServingEngine(fwd, {}, cfg, batch_size=1, max_len=96,
                      host_cache_bytes=-1)
    with pytest.raises(ValueError):
        ServingEngine(fwd, {}, cfg, batch_size=1, max_len=96,
                      host_cache_dtype="fp4")
    with pytest.raises(ValueError):
        ServingEngine(fwd, {}, cfg, batch_size=1, max_len=96,
                      kv_block_size=0, kv_pool_dtype="int8")


def test_engine_kill_mid_decode_keeps_spilled_tier_coherent():
    """Kill-mid-decode with the host tier live: cancel fires at a wave
    boundary while spilled entries exist — the drain must leave the
    pool partition leak-free (free + parked == pool, allocated ==
    reserved == 0) AND the spilled tier coherent (tree markers == store
    payloads), with the drained snapshot intact for the failover
    planner."""
    from nexus_tpu.utils.signals import CancelToken

    v = 13
    cfg, fwd = _cyclic_model(v)
    reqs = _pressure_queue(v, np.random.RandomState(7), repeats=4)
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
        kv_block_size=8, kv_num_blocks=4, prefix_cache=True,
        admission_policy="fifo", host_cache_bytes=1 << 20,
    )
    eng._sanitize = True
    cancel = CancelToken()
    fired = []

    def heartbeat(committed):
        # let the run make real progress (spills + at least one restore
        # wave) before the kill
        if committed >= 24 and not fired:
            fired.append(True)
            cancel.cancel(hard=True)

    results, m = eng.serve(reqs, cancel=cancel, heartbeat=heartbeat)
    assert m["interrupted"] is True
    assert eng.last_drain  # something was in flight or queued
    assert m["kv_allocated_blocks_final"] == 0
    assert m["kv_reserved_blocks_final"] == 0
    assert (m["kv_free_blocks_final"] + m["kv_parked_blocks_final"]
            == m["kv_num_blocks"])
    assert (m["kv_spilled_blocks_final"]
            == m["host_cache_entries_final"])
    # the audits themselves (what NEXUS_SANITIZE wraps) must pass
    from nexus_tpu.testing.sanitizers import (
        audit_host_cache,
        audit_pool_partition,
        audit_prefix_tree,
    )

    audit_pool_partition(m, context="kill-mid-decode")
    audit_prefix_tree(eng, context="kill-mid-decode")
    audit_host_cache(eng, context="kill-mid-decode")


def test_engine_host_tier_inert_without_prefix_cache():
    """hostCacheBytes without the prefix cache is inert (nothing could
    ever be re-matched): no store is built, no spill metrics appear."""
    v = 7
    cfg, fwd = _cyclic_model(v)
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=64, chunk=4,
        kv_block_size=8, prefix_cache=False, host_cache_bytes=1 << 20,
    )
    results, m = eng.serve(
        [ServeRequest(prompt=[1, 2, 3], max_new_tokens=4)]
    )
    assert results[0].tokens == _expect(
        ServeRequest(prompt=[1, 2, 3], max_new_tokens=4), v
    )
    assert eng.last_host_store is None
    assert "spilled_blocks" not in m


# ---------------------------------------------------------- spec surface


def test_serve_spec_tiered_knobs_roundtrip_and_validation():
    """hostCacheBytes / hostCacheDtype / kvPoolDtype: dict roundtrip
    (defaults omitted, values preserved) and the validation rules — the
    spill tier needs the paged layout AND the prefix cache, dtypes are
    a closed set, and the int8 pool is paged-only."""
    from nexus_tpu.api.runtime_spec import (
        JaxXlaRuntime, ModelRef, ParallelismSpec, ServeSpec,
        TpuSliceSpec, TrainSpec,
    )

    spec = ServeSpec(kv_pool_dtype="int8", host_cache_bytes=1 << 30,
                     host_cache_dtype="int8")
    d = spec.to_dict()
    assert d["kvPoolDtype"] == "int8"
    assert d["hostCacheBytes"] == 1 << 30
    assert d["hostCacheDtype"] == "int8"
    rt = ServeSpec.from_dict(d)
    assert rt.kv_pool_dtype == "int8"
    assert rt.host_cache_bytes == 1 << 30
    assert rt.host_cache_dtype == "int8"
    # defaults stay OFF the wire and survive the roundtrip
    dd = ServeSpec().to_dict()
    assert "kvPoolDtype" not in dd and "hostCacheBytes" not in dd
    back = ServeSpec.from_dict(dd)
    assert back.kv_pool_dtype == "native"
    assert back.host_cache_bytes == 0
    assert back.host_cache_dtype == "native"

    def mk(serve):
        return JaxXlaRuntime(
            mode="serve",
            model=ModelRef(family="llama", preset="tiny",
                           overrides={"dtype": "float32"}),
            tpu=TpuSliceSpec(accelerator="v5e", topology="1x1",
                             slice_count=1),
            parallelism=ParallelismSpec(),
            train=TrainSpec(batch_size=4, seq_len=64),
            serve=serve,
        )

    assert mk(ServeSpec(host_cache_bytes=1 << 30,
                        kv_pool_dtype="int8")).validate() == []
    errs = mk(ServeSpec(kv_pool_dtype="fp4")).validate()
    assert any("kvPoolDtype" in e for e in errs), errs
    errs = mk(ServeSpec(kv_pool_dtype="int8",
                        kv_block_size=0)).validate()
    assert any("kvPoolDtype" in e for e in errs), errs
    errs = mk(ServeSpec(host_cache_bytes=-1)).validate()
    assert any("hostCacheBytes" in e for e in errs), errs
    errs = mk(ServeSpec(host_cache_dtype="fp4")).validate()
    assert any("hostCacheDtype" in e for e in errs), errs
    errs = mk(ServeSpec(host_cache_bytes=1 << 30,
                        kv_block_size=0)).validate()
    assert any("paged layout" in e for e in errs), errs
    errs = mk(ServeSpec(host_cache_bytes=1 << 30,
                        prefix_cache=False)).validate()
    assert any("prefixCache" in e for e in errs), errs
    # the HBM gate prices an int8 pool at ~1 byte/element + scales:
    # same spec, quantized pool → materially smaller cache footprint
    fp = mk(ServeSpec()).hbm_budget_gb()
    q = mk(ServeSpec(kv_pool_dtype="int8")).hbm_budget_gb()
    assert q["kv_cache_gb"] < fp["kv_cache_gb"]


def test_admit_restore_survives_drain_of_pending_digest():
    """Review regression (round 10): a spill triggered inside admit()'s
    restore loop pushes the store over budget — the drain must NOT drop
    a digest still pending in THIS admission's restore list (it is a
    spilled full leaf until its turn comes). Pre-fix this raised
    ValueError('digest is not spilled') mid-mutation and leaked the
    just-taken pool block; the drain now runs at the admit boundary,
    when every pending digest is resident and undroppable."""
    rng = np.random.RandomState(11)
    one_block = sum(a.nbytes for a in _planes(rng).values())
    # budget ~1.5 blocks: holding chain A's spilled block plus the
    # spill admit() itself triggers goes over budget mid-loop
    alloc, idx, store, oracle = _fake_spill_env(
        num_blocks=2, budget=one_block + one_block // 2
    )
    keys_a = chain_keys(list(range(BLOCK_SIZE)), BLOCK_SIZE)
    keys_b = chain_keys(list(range(50, 50 + BLOCK_SIZE)), BLOCK_SIZE)
    # chain A: registered, parked, then spilled under pressure
    la = alloc.admit(1)
    (a0,) = la.grow_to(1)
    alloc.register_block(keys_a[0], a0)
    la.release()
    lb = alloc.admit(2)
    b0, b1 = lb.grow_to(2)
    assert alloc.spills == 1 and set(store.keys()) == {keys_a[0]}
    # chain B: registered on one block, parked
    alloc.register_block(keys_b[0], b0)
    lb.release()
    assert idx.parked_count == 1  # b0 parked, b1 freed
    # the poisoned admission: restoring A0 must _take_block -> spill b0
    # -> store momentarily holds A0 + B0 (over budget) -> pre-fix the
    # drain dropped A0 right before index.restore(A0)
    lease = alloc.admit(0, restore=[keys_a[0]])
    assert lease is not None, "restoring admission crashed or refused"
    assert lease.shared and idx.holder(keys_a[0]) == lease.shared[0]
    _assert_restore_fidelity(lease, oracle, "native")
    # boundary drain ran: back under budget, store/tree coherent
    assert not store.over_budget()
    assert set(store.keys()) == set(idx._spilled)
    idx.audit()
    store.audit()
    lease.release()
    _check_tiered_pool(alloc, idx, store, num_blocks=2)


def _check_tiered_pool(alloc, idx, store, num_blocks):
    """Partition + coherence for a drained allocator of any size."""
    free = set(alloc._free)
    parked = set(idx._parked)
    referenced = {
        b for b in range(num_blocks) if alloc._ref[b] > 0
    }
    assert not referenced, "leaked lease"
    assert free | parked == set(range(num_blocks))
    assert set(store.keys()) == set(idx._spilled)
    idx.audit()
    store.audit()


def test_custom_int_policy_contract_survives_without_host_tier():
    """Round-9 API compatibility: a user-supplied AdmissionPolicy whose
    order() treats the ranking signal as a plain int (the documented
    round-9 contract) keeps working on engines WITHOUT a host tier —
    the tiered (resident, spilled) pair only arrives once
    host_cache_bytes attaches one, exactly as scheduling.py's docstring
    promises."""
    from nexus_tpu.runtime.scheduling import AdmissionPolicy

    seen_types = []

    class IntRanked(AdmissionPolicy):
        name = "int-ranked"

        def order(self, pending, passed_over, resident_match):
            # negating the signal: crashes on a tuple (TypeError)
            ranked = sorted(pending,
                            key=lambda i: -resident_match(i))
            for i in pending:
                seen_types.append(type(resident_match(i)))
            return ranked

    v = 13
    cfg, fwd = _cyclic_model(v)
    reqs = _pressure_queue(v, np.random.RandomState(5))
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
        kv_block_size=8, kv_num_blocks=4, prefix_cache=True,
        admission_policy=IntRanked(),
    )
    results, m = eng.serve(reqs)
    for req, res in zip(reqs, results):
        assert res.tokens == _expect(req, v)
    assert all(t is int for t in seen_types)
    assert m["admission_policy"] == "int-ranked"
    # with the tier attached, the pair form arrives — and the shipped
    # cache-aware policy accepts both (normalized in _tiers)
    seen_types.clear()
    eng2 = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=96, chunk=4,
        kv_block_size=8, kv_num_blocks=4, prefix_cache=True,
        host_cache_bytes=1 << 20,
    )
    results2, m2 = eng2.serve(reqs)
    for req, res in zip(reqs, results2):
        assert res.tokens == _expect(req, v)
