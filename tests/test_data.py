"""Token-corpus pipeline: memmap batches, host-disjoint sharding, prefetch,
runtime wiring, and the ErrorHandlingBehaviour -> podFailurePolicy mapping."""

import numpy as np
import pytest

from nexus_tpu.train.data import (
    Prefetcher,
    token_file_batches,
    write_token_file,
)


def make_corpus(tmp_path, n=4096, dtype="int32"):
    path = str(tmp_path / "corpus.bin")
    tokens = np.arange(n) % 251  # deterministic, recognizable
    write_token_file(path, tokens, dtype=dtype)
    return path, tokens


def test_token_file_batches_shapes_and_content(tmp_path):
    path, tokens = make_corpus(tmp_path)
    it = token_file_batches(path, batch_size=4, seq_len=16, seed=3)
    batch = next(it)
    assert batch["tokens"].shape == (4, 17)
    assert batch["tokens"].dtype == np.int32
    # every row must be a contiguous window of the corpus
    for row in batch["tokens"]:
        start = int(row[0])
        # corpus is arange % 251, so reconstruct and compare
        idx = np.where(tokens == start)[0]
        assert any(
            np.array_equal(tokens[i:i + 17], row) for i in idx if i + 17 <= len(tokens)
        )


def test_token_file_batches_shards_are_disjoint(tmp_path):
    # corpus of unique values → a window's content identifies its position
    path = str(tmp_path / "uniq.bin")
    write_token_file(path, np.arange(2000))
    a = next(token_file_batches(path, 64, 8, shard_index=0, num_shards=2))
    b = next(token_file_batches(path, 64, 8, shard_index=1, num_shards=2))
    # regions are [0, 1000) and [1000, 2000): every shard-0 token < 1000,
    # every shard-1 token >= 1000
    assert a["tokens"].max() < 1000
    assert b["tokens"].min() >= 1000


def test_token_file_batches_validates(tmp_path):
    path, _ = make_corpus(tmp_path, n=10)
    with pytest.raises(ValueError, match="need >="):
        next(token_file_batches(path, 1, 64))
    with pytest.raises(ValueError, match="shard_index"):
        next(token_file_batches(path, 1, 4, shard_index=2, num_shards=2))


def test_token_file_dtype_uint16(tmp_path):
    path, _ = make_corpus(tmp_path, dtype="uint16")
    batch = next(token_file_batches(path, 2, 8, dtype="uint16"))
    assert batch["tokens"].dtype == np.int32  # always widened for embedding


def test_prefetcher_delivers_and_closes(tmp_path):
    path, _ = make_corpus(tmp_path)
    it = token_file_batches(path, 2, 8)
    pf = Prefetcher(it, depth=2)
    seen = [next(pf) for _ in range(5)]
    assert all(b["tokens"].shape == (2, 9) for b in seen)
    pf.close()
    # bounded iterator: exhaustion produces StopIteration
    lst = Prefetcher(iter([{"x": 1}, {"x": 2}]), depth=1)
    assert list(lst) == [{"x": 1}, {"x": 2}]


def test_runtime_trains_from_token_corpus(tmp_path):
    from nexus_tpu.api.runtime_spec import (
        DataSpec, JaxXlaRuntime, ModelRef, ParallelismSpec, TpuSliceSpec,
        TrainSpec,
    )
    from nexus_tpu.runtime.entrypoints import run_template_runtime

    path, _ = make_corpus(tmp_path)
    rt = JaxXlaRuntime(
        mode="train",
        model=ModelRef(family="llama", preset="tiny",
                       overrides={"dtype": "float32", "attn_impl": "xla"}),
        tpu=TpuSliceSpec(accelerator="v5e", topology="1x1", slice_count=1),
        parallelism=ParallelismSpec(),
        train=TrainSpec(batch_size=2, seq_len=32, steps=3, learning_rate=1e-3),
        data=DataSpec(kind="tokens", path=path),
    )
    metrics = run_template_runtime(rt)
    assert metrics["steps"] == 3
    assert np.isfinite(metrics["final_loss"])


def test_data_spec_validation():
    from nexus_tpu.api.runtime_spec import DataSpec, JaxXlaRuntime

    rt = JaxXlaRuntime(data=DataSpec(kind="tokens", path=""))
    assert any("data.path" in e for e in rt.validate())
    rt2 = JaxXlaRuntime(data=DataSpec(kind="bogus"))
    assert any("data.kind" in e for e in rt2.validate())
    rt3 = JaxXlaRuntime.from_dict(
        JaxXlaRuntime(data=DataSpec(kind="tokens", path="/x", prefetch=0)).to_dict()
    )
    assert rt3.data.prefetch == 0 and rt3.data.path == "/x"


def test_materializer_pod_failure_policy():
    from nexus_tpu.runtime.materializer import materialize_job
    from tests.test_runtime import template_with_runtime

    tmpl = template_with_runtime()
    tmpl.spec.error_handling_behaviour.fatal_exit_codes = [13, 7]
    tmpl.spec.error_handling_behaviour.transient_exit_codes = [42]
    job = materialize_job(tmpl)[0]
    rules = job["spec"]["podFailurePolicy"]["rules"]
    assert rules[0]["action"] == "FailJob"
    assert rules[0]["onExitCodes"]["values"] == [7, 13]
    assert rules[1]["action"] == "Ignore"
    # 75 (EXIT_PREEMPTED) is always transient
    assert rules[1]["onExitCodes"]["values"] == [42, 75]

    # with no declared codes, the preemption rule still exists
    tmpl2 = template_with_runtime()
    job2 = materialize_job(tmpl2)[0]
    rules2 = job2["spec"]["podFailurePolicy"]["rules"]
    assert len(rules2) == 1 and rules2[0]["onExitCodes"]["values"] == [75]

    # a template may declare 75 fatal; fatal wins
    tmpl3 = template_with_runtime()
    tmpl3.spec.error_handling_behaviour.fatal_exit_codes = [75]
    rules3 = materialize_job(tmpl3)[0]["spec"]["podFailurePolicy"]["rules"]
    assert rules3[0]["action"] == "FailJob"
    assert rules3[0]["onExitCodes"]["values"] == [75]
    assert len(rules3) == 1


def test_prefetcher_surfaces_pipeline_errors(tmp_path):
    it = token_file_batches(str(tmp_path / "missing.bin"), 2, 8)
    pf = Prefetcher(it, depth=1)
    with pytest.raises(FileNotFoundError):
        next(pf)


def test_token_file_vocab_guard(tmp_path):
    path = str(tmp_path / "big.bin")
    write_token_file(path, np.full(100, 50_000))
    it = token_file_batches(path, 2, 8, vocab_size=32_000)
    with pytest.raises(ValueError, match="outside"):
        next(it)


def test_materializer_filters_exit_code_zero():
    from nexus_tpu.runtime.materializer import materialize_job
    from tests.test_runtime import template_with_runtime

    tmpl = template_with_runtime()
    tmpl.spec.error_handling_behaviour.fatal_exit_codes = [0]
    job = materialize_job(tmpl)[0]
    rules = job["spec"]["podFailurePolicy"]["rules"]
    # 0 filtered out of fatal; only the standing preemption rule remains
    assert len(rules) == 1 and rules[0]["action"] == "Ignore"


def test_native_token_loader_contract(tmp_path):
    """Native C++ reader: same sampling contract as the Python path."""
    from nexus_tpu.native import available

    if not available():
        pytest.skip("native library unavailable")
    from nexus_tpu.native import NativeTokenLoader

    path = str(tmp_path / "uniq.bin")
    write_token_file(path, np.arange(4000))
    ldr = NativeTokenLoader(path, batch_size=8, seq_len=16, seed=5)
    b1, b2 = next(ldr), next(ldr)
    assert b1["tokens"].shape == (8, 17)
    assert b1["tokens"].dtype == np.int32
    # windows are contiguous runs of the corpus (unique values: row == arange)
    for row in b1["tokens"]:
        assert np.array_equal(row, np.arange(row[0], row[0] + 17))
    # streams advance (overwhelmingly unlikely to repeat the exact batch)
    assert not np.array_equal(b1["tokens"], b2["tokens"])
    # deterministic per (seed, shard)
    ldr2 = NativeTokenLoader(path, batch_size=8, seq_len=16, seed=5)
    np.testing.assert_array_equal(next(ldr2)["tokens"], b1["tokens"])
    ldr.close(); ldr2.close()

    # shard disjointness
    a = next(NativeTokenLoader(path, 32, 8, shard_index=0, num_shards=2))
    b = next(NativeTokenLoader(path, 32, 8, shard_index=1, num_shards=2))
    assert a["tokens"].max() < 2000
    assert b["tokens"].min() >= 2000

    # vocab guard + uint16 + open failures
    ldrv = NativeTokenLoader(path, 2, 8, vocab_size=100)
    with pytest.raises(ValueError, match="vocab_size"):
        next(ldrv)
    path16 = str(tmp_path / "u16.bin")
    write_token_file(path16, np.arange(1000) % 500, dtype="uint16")
    b16 = next(NativeTokenLoader(path16, 2, 8, dtype="uint16"))
    assert b16["tokens"].dtype == np.int32 and b16["tokens"].max() < 500
    with pytest.raises(ValueError, match="ncd_open"):
        NativeTokenLoader(str(tmp_path / "nope.bin"), 2, 8)


def test_corpus_batches_backends_agree_on_contract(tmp_path):
    from nexus_tpu.train.data import corpus_batches

    path = str(tmp_path / "uniq.bin")
    write_token_file(path, np.arange(3000))
    for backend in ("python", "auto"):
        b = next(corpus_batches(path, 4, 8, backend=backend))
        assert b["tokens"].shape == (4, 9)
        for row in b["tokens"]:
            assert np.array_equal(row, np.arange(row[0], row[0] + 9))
    with pytest.raises(ValueError, match="backend"):
        corpus_batches(path, 4, 8, backend="gpu")


def test_tokens_data_rejected_for_mlp():
    from nexus_tpu.api.runtime_spec import DataSpec, JaxXlaRuntime, ModelRef

    rt = JaxXlaRuntime(
        model=ModelRef(family="mlp"), data=DataSpec(kind="tokens", path="/x")
    )
    assert any("mlp" in e for e in rt.validate())


def test_negative_token_ids_rejected(tmp_path):
    path = str(tmp_path / "neg.bin")
    write_token_file(path, np.array([5, -3] * 50))
    with pytest.raises(ValueError, match="outside"):
        next(token_file_batches(path, 2, 8, vocab_size=100))
    from nexus_tpu.native import available

    if available():
        from nexus_tpu.native import NativeTokenLoader

        with pytest.raises(ValueError, match="negative"):
            next(NativeTokenLoader(path, 2, 8))
