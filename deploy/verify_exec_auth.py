"""Image-level exec-auth verification (CI runs this INSIDE the built
container; it also runs on a dev box).

Two assertions mirroring why the reference bundles the AWS CLI in its
image (/root/reference/.container/Dockerfile:16-31, README.md:30):

  1. the exec-credential plugin binaries a GKE/EKS shard kubeconfig
     names (``gke-gcloud-auth-plugin``, ``aws``) resolve on PATH —
     unless AUTH_PLUGINS trimmed them at build time (pass --no-plugins);
  2. the controller's own ExecCredentialPlugin (cluster/kubeapi.py) can
     spawn a plugin from PATH and mint a bearer token end to end — a
     STUB plugin is written to a temp dir, prepended to PATH, and must
     produce the token through the real subprocess + JSON-parse flow.

    docker run --rm -v $PWD:/src --entrypoint python IMAGE \
        /src/deploy/verify_exec_auth.py
"""

from __future__ import annotations

import os
import shutil
import stat
import sys
import tempfile

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)
if os.path.isdir("/app"):
    sys.path.insert(0, "/app")  # image layout

from nexus_tpu.cluster.kubeapi import ExecCredentialPlugin  # noqa: E402

STUB = """#!/bin/sh
echo '{"apiVersion":"client.authentication.k8s.io/v1",\
"kind":"ExecCredential",\
"status":{"token":"stub-token-123",\
"expirationTimestamp":"2099-01-01T00:00:00Z"}}'
"""


def main() -> int:
    check_binaries = "--no-plugins" not in sys.argv
    failures = []
    if check_binaries:
        for binary in ("aws", "gke-gcloud-auth-plugin"):
            path = shutil.which(binary)
            if path:
                print(f"ok: {binary} -> {path}")
            else:
                failures.append(f"{binary} not on PATH")
    with tempfile.TemporaryDirectory() as tmp:
        stub = os.path.join(tmp, "stub-auth-plugin")
        with open(stub, "w") as f:
            f.write(STUB)
        os.chmod(stub, os.stat(stub).st_mode | stat.S_IEXEC)
        os.environ["PATH"] = tmp + os.pathsep + os.environ.get("PATH", "")
        plugin = ExecCredentialPlugin({"command": "stub-auth-plugin"})
        token = plugin.token()
        if token == "stub-token-123":
            print("ok: ExecCredentialPlugin minted a token via PATH")
        else:
            failures.append(f"unexpected token {token!r}")
    if failures:
        for f_ in failures:
            print(f"FAIL: {f_}")
        return 1
    print("exec-auth verification passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
