{{- define "nexus-tpu.name" -}}
{{- .Chart.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "nexus-tpu.labels" -}}
app.kubernetes.io/name: {{ include "nexus-tpu.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}

{{- define "nexus-tpu.serviceAccountName" -}}
{{- if .Values.serviceAccount.create -}}
{{- default (include "nexus-tpu.name" .) .Values.serviceAccount.name -}}
{{- else -}}
{{- .Values.serviceAccount.name -}}
{{- end -}}
{{- end -}}
