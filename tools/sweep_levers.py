"""On-chip profiling battery for the opt-in perf levers (VERDICT r2 item 5).

Runs each lever's A/B measurement on the attached accelerator and appends
one JSON line per result to stdout (and NEXUS_SWEEP_OUT if set), so a
partially-completed battery still yields numbers:

  * moe-dispatch   — einsum (T,E,C) contraction vs scatter/gather token
                     movement at Mixtral-layer shapes;
  * window-flash   — sliding-window flash kernel fwd+grad wall time vs the
                     windowless kernel at long sequence (tile-skipping);
  * run-ahead      — trainer dispatch depth 1/2/4/8 steps/sec (hides the
                     host↔device round-trip);
  * (int8 KV and speculative decode are covered by bench.py's decode
     suite — same artifact, no duplication here.)

Each phase is wrapped in its own try/except and the whole battery sits
under an internal watchdog (NEXUS_SWEEP_DEADLINE_S, default 2400) — the
TPU tunnel wedging mid-phase must not hang the caller, and no external
killer should be needed (killing a TPU process mid-RPC wedges the tunnel,
docs/PERF.md).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit(rec: dict) -> None:
    line = json.dumps(rec)
    print(line, flush=True)
    out = os.environ.get("NEXUS_SWEEP_OUT", "")
    if out:
        with open(out, "a") as f:
            f.write(line + "\n")


def _sync(out):
    """Force real device completion (host-fetch-bounded; see
    ``nexus_tpu.utils.hw.sync_host`` for why ``block_until_ready`` alone
    is not trustworthy on the axon tunnel platform)."""
    from nexus_tpu.utils.hw import sync_host

    sync_host(out)


def _timed(fn, *args, iters=20, warmup=3):
    """Scan-amortized wall time per call of ``fn(*args)``.

    Per-dispatch timing over the axon tunnel is hopeless: the ~71 ms
    round-trip jitters by tens of ms run-to-run, swamping millisecond-scale
    kernels (observed: the same window-flash grad A/B read 1.06x, 1.44x and
    1.98x on three consecutive per-dispatch runs). Instead run ``iters``
    loop-carried iterations inside ONE ``lax.scan`` dispatch so the
    round-trip is paid once per measurement, not per iteration.

    Hoisting guard: the body is loop-invariant (same ``args`` every tick),
    so XLA's licm would compute ``fn`` once unless each tick depends on the
    previous one. The carry (one scalar read from the previous output) is
    folded into the first float input scaled by ``eps`` — a RUNTIME zero
    argument, which XLA cannot constant-fold away — keeping the numerics of
    every tick bit-identical to ``fn(*args)`` while forcing sequential
    execution."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    p = next(i for i, l in enumerate(leaves)
             if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.inexact))

    def scanned(eps, *leaves):
        def body(carry, _):
            perturbed = list(leaves)
            perturbed[p] = leaves[p] + (eps * carry).astype(leaves[p].dtype)
            out = fn(*jax.tree_util.tree_unflatten(treedef, perturbed))
            # EVERY output leaf must feed the carry: a multi-output fn
            # (e.g. grad tuples whose dq and dk/dv come from separate
            # pallas_calls) would otherwise have its unused outputs — and
            # the kernels producing them — dead-code-eliminated, timing
            # only part of the computation
            acc = jnp.float32(0.0)
            for lf in jax.tree_util.tree_leaves(out):
                acc = acc + lf.ravel()[0].astype(jnp.float32)
            return acc, None
        return lax.scan(body, jnp.float32(0.0), None, length=iters)[0]

    run = jax.jit(scanned)
    eps = jnp.float32(0.0)
    out = None
    for _ in range(max(warmup, 1)):  # compile + steady-state passes
        out = run(eps, *leaves)
        _sync(out)
    # one round-trip (the scalar fetch) still sits inside each window;
    # measure it on the already-ready output and subtract. Best-of-3 on
    # BOTH sides: a latency spike in a single sync_cost sample would
    # over-subtract from every window (driving short measurements to the
    # floor), just as a spike mid-window would inflate one measurement.
    sync_cost = None
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(out)
        dt = time.perf_counter() - t0
        sync_cost = dt if sync_cost is None or dt < sync_cost else sync_cost
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(run(eps, *leaves))
        dt = time.perf_counter() - t0 - sync_cost
        best = dt if best is None or dt < best else best
    return max(best, 1e-9) / iters


def phase_moe_dispatch():
    """Dense-einsum vs scatter dispatch+combine at a Mixtral-8x7B-ish
    single-chip layer shape."""
    import jax
    import jax.numpy as jnp

    from nexus_tpu.ops.moe import (
        moe_combine_dense,
        moe_combine_scatter,
        moe_dispatch_dense,
        moe_dispatch_scatter,
        top_k_routing,
    )

    from nexus_tpu.utils.hw import is_tpu

    # tokens = batch*seq at bench shape; d scaled to fit one v5e.
    # Off-TPU this is a smoke test of the harness, not a measurement —
    # the TPU shapes take minutes per window on a host CPU.
    if is_tpu():
        t_tokens, d, e, k = 4096, 1024, 8, 2
    else:
        t_tokens, d, e, k = 256, 64, 4, 2
    capacity = int(1.25 * k * t_tokens / e)
    x = jax.random.normal(jax.random.PRNGKey(0), (t_tokens, d), jnp.bfloat16)
    logits = jax.random.normal(jax.random.PRNGKey(1), (t_tokens, e), jnp.float32)
    routing = jax.jit(
        functools.partial(top_k_routing, num_selected=k, capacity=capacity)
    )(logits)
    jax.block_until_ready(routing)

    def einsum_path(x, routing):
        buf = moe_dispatch_dense(x, routing)
        return moe_combine_dense(buf, routing)

    def scatter_path(x, routing):
        buf = moe_dispatch_scatter(x, routing, e, capacity)
        return moe_combine_scatter(buf, routing)

    te = _timed(jax.jit(einsum_path), x, routing)
    ts = _timed(jax.jit(scatter_path), x, routing)
    _emit({
        "phase": "moe-dispatch", "tokens": t_tokens, "d_model": d,
        "experts": e, "top_k": k,
        "einsum_ms": round(te * 1e3, 3), "scatter_ms": round(ts * 1e3, 3),
        "scatter_speedup": round(te / ts, 3) if ts else None,
    })


def phase_window_flash():
    """Sliding-window tile-skipping: fwd + grad at long sequence.

    Numerics gate first: the windowed kernels run their grid COMPACTED
    (attention.py::_window_tile_span) — interpret-mode tests can't see a
    real-lowering index bug, so on TPU the phase validates fwd + grads
    against the XLA reference at a compaction-engaging shape before any
    timing, and emits the verdict."""
    import jax
    import jax.numpy as jnp

    from nexus_tpu.ops.attention import attention_xla, flash_attention

    from nexus_tpu.utils.hw import is_tpu

    if is_tpu():
        b, s, hq, hkv, dh = 1, 8192, 8, 4, 128
        window = 1024
        it_f, it_g = 30, 15

        vq, vk, vv = (
            jax.random.normal(kk, (1, 2048, 4 if i == 0 else 2, 128),
                              jnp.bfloat16)
            for i, kk in enumerate(
                jax.random.split(jax.random.PRNGKey(7), 3)
            )
        )

        def _ref_loss(q_, k_, v_):
            return (attention_xla(q_, k_, v_, causal=True, window=512)
                    .astype(jnp.float32) ** 2).sum()

        def _fl_loss(q_, k_, v_):
            # 256-blocks: 8 k tiles vs a 4-tile window footprint — the
            # compacted grids are definitely the code path under test
            return (flash_attention(q_, k_, v_, causal=True, window=512,
                                    block_q=256, block_k=256,
                                    interpret=False)
                    .astype(jnp.float32) ** 2).sum()

        def _close(a_, b_):
            a32 = jnp.asarray(a_, jnp.float32)
            b32 = jnp.asarray(b_, jnp.float32)
            scale = float(jnp.max(jnp.abs(a32))) or 1.0
            return float(jnp.max(jnp.abs(a32 - b32))) / scale < 2e-2

        ref_o = attention_xla(vq, vk, vv, causal=True, window=512)
        fl_o = flash_attention(vq, vk, vv, causal=True, window=512,
                               block_q=256, block_k=256, interpret=False)
        ref_g = jax.jit(jax.grad(_ref_loss, argnums=(0, 1, 2)))(vq, vk, vv)
        fl_g = jax.jit(jax.grad(_fl_loss, argnums=(0, 1, 2)))(vq, vk, vv)
        _sync((ref_g, fl_g))
        parity = _close(ref_o, fl_o) and all(
            _close(a_, b_) for a_, b_ in zip(ref_g, fl_g)
        )
        _emit({"phase": "window-flash-parity", "on_chip": True,
               "window": 512, "seq": 2048, "ok": bool(parity)})
        if not parity:
            return  # timing a wrong kernel is worse than no number
    else:  # smoke shape: interpret-mode pallas on CPU is minutes-slow
        b, s, hq, hkv, dh = 1, 512, 2, 1, 64
        window = 128
        it_f, it_g = 2, 2
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, hq, dh), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, hkv, dh), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, hkv, dh), jnp.bfloat16)

    def fwd(w):
        return jax.jit(
            lambda q, k, v: flash_attention(q, k, v, causal=True, window=w)
        )

    def grad(w):
        return jax.jit(jax.grad(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, window=w
            ).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        ))

    tf_full = _timed(fwd(0), q, k, v, iters=it_f)
    tf_win = _timed(fwd(window), q, k, v, iters=it_f)
    tg_full = _timed(grad(0), q, k, v, iters=it_g)
    tg_win = _timed(grad(window), q, k, v, iters=it_g)
    _emit({
        "phase": "window-flash", "seq": s, "window": window,
        "fwd_full_ms": round(tf_full * 1e3, 3),
        "fwd_window_ms": round(tf_win * 1e3, 3),
        "fwd_speedup": round(tf_full / tf_win, 3),
        "grad_full_ms": round(tg_full * 1e3, 3),
        "grad_window_ms": round(tg_win * 1e3, 3),
        "grad_speedup": round(tg_full / tg_win, 3),
    })


def phase_run_ahead():
    """Trainer dispatch depth: steps/sec at depth 1 vs 2 vs 4 vs 8."""
    from nexus_tpu.api.runtime_spec import (
        JaxXlaRuntime,
        ModelRef,
        ParallelismSpec,
        TpuSliceSpec,
        TrainSpec,
    )
    from nexus_tpu.runtime.entrypoints import run_template_runtime
    from nexus_tpu.utils.hw import is_tpu

    preset = "400m" if is_tpu() else "tiny"
    seq = 2048 if is_tpu() else 64
    out = {"phase": "run-ahead", "preset": preset, "seq": seq}
    for depth in (1, 2, 4, 8):
        os.environ["NEXUS_RUN_AHEAD"] = str(depth)
        try:
            runtime = JaxXlaRuntime(
                mode="train",
                model=ModelRef(
                    family="llama", preset=preset,
                    # the bench's measured operating point: flash attention
                    # + dots remat (remat=none OOMs the v5e compile helper
                    # at this shape, docs/PERF.md round-3 sweep)
                    overrides=(
                        {"attn_impl": "flash", "remat": True,
                         "remat_policy": "dots"}
                        if is_tpu() else {"dtype": "float32"}
                    ),
                ),
                tpu=TpuSliceSpec(accelerator="v5e", topology="1x1"),
                parallelism=ParallelismSpec(),
                train=TrainSpec(batch_size=8, seq_len=seq, steps=12,
                                learning_rate=3e-4),
            )
            m = run_template_runtime(runtime)
            out[f"steps_per_sec_depth{depth}"] = round(
                m.get("steps_per_sec", 0.0), 4
            )
        except Exception as e:  # noqa: BLE001
            out[f"depth{depth}_error"] = f"{type(e).__name__}: {str(e)[:120]}"
        finally:
            os.environ.pop("NEXUS_RUN_AHEAD", None)
    _emit(out)


PHASES = {
    "moe-dispatch": phase_moe_dispatch,
    "window-flash": phase_window_flash,
    "run-ahead": phase_run_ahead,
}


def main() -> int:
    import threading

    deadline = float(os.environ.get("NEXUS_SWEEP_DEADLINE_S") or 2400)
    stage = ["startup"]

    def watchdog():
        _emit({"phase": "watchdog", "error": f"deadline {deadline}s hit "
               f"at stage {stage[0]}"})
        os._exit(1)

    timer = threading.Timer(deadline, watchdog)
    timer.daemon = True
    timer.start()

    only = [a for a in sys.argv[1:] if not a.startswith("-")]
    from nexus_tpu.utils.hw import device_kind, honor_env_platforms

    honor_env_platforms()
    stage[0] = "backend-init"
    import jax

    _emit({"phase": "backend", "device": device_kind(),
           "n_devices": len(jax.devices())})
    rc = 0
    for name, fn in PHASES.items():
        if only and name not in only:
            continue
        stage[0] = name
        try:
            fn()
        except Exception as e:  # noqa: BLE001
            _emit({"phase": name,
                   "error": f"{type(e).__name__}: {str(e)[:300]}"})
            rc = 1
    timer.cancel()
    return rc


if __name__ == "__main__":
    sys.exit(main())
