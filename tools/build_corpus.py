"""Build a flat binary token corpus from text files.

Connects the tokenizer (utils/tokenizer.py — the same tokenizer.json an
inference template uses) to the training data plane: the output is the
headerless little-endian token file `train/data.py::token_file_batches`
and the native C++ reader (native/src/nexus_data.cpp) mmap directly.

    python tools/build_corpus.py --tokenizer tokenizer.json \
        --out corpus.bin --dtype uint16 input1.txt input2.txt ...

Documents separated by ``--separator-id`` (default: none). dtype uint16
halves corpus disk/IO for vocabularies < 65536 (not Llama-3's 128k —
use int32 there; the builder validates ids fit).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from nexus_tpu.train.data import TOKEN_DTYPES  # noqa: E402
from nexus_tpu.utils.tokenizer import load_tokenizer  # noqa: E402


def build_corpus(
    inputs,
    tokenizer_path: str,
    out_path: str,
    dtype: str = "int32",
    separator_id: int = -1,
    engine: str = "auto",
) -> int:
    """Tokenize ``inputs`` (paths or file objects) into ``out_path``.
    Returns the total token count. Streams file-by-file — the whole corpus
    is never resident."""
    if dtype not in TOKEN_DTYPES:
        raise ValueError(
            f"dtype {dtype!r} not in {sorted(TOKEN_DTYPES)}"
        )
    np_dtype = TOKEN_DTYPES[dtype]
    limit = np.iinfo(np_dtype).max
    tok = load_tokenizer(tokenizer_path, engine=engine)
    total = 0
    with open(out_path, "wb") as out:
        for src in inputs:
            if hasattr(src, "read"):
                text = src.read()
            else:
                with open(src, encoding="utf-8") as f:
                    text = f.read()
            ids = tok.encode(text)
            if separator_id >= 0:
                ids = ids + [separator_id]
            if ids and max(ids) > limit:
                raise ValueError(
                    f"token id {max(ids)} exceeds dtype {dtype} "
                    f"(max {limit}); use a wider dtype"
                )
            np.asarray(ids, dtype=np_dtype).tofile(out)
            total += len(ids)
    return total


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("inputs", nargs="+", help="text files to tokenize")
    p.add_argument("--tokenizer", required=True, help="tokenizer.json path")
    p.add_argument("--out", required=True, help="output corpus path")
    p.add_argument("--dtype", default="int32",
                   choices=sorted(TOKEN_DTYPES))
    p.add_argument("--separator-id", type=int, default=-1,
                   help="token id appended after each document (-1 = none)")
    p.add_argument("--engine", default="auto",
                   choices=("auto", "rust", "pure"))
    args = p.parse_args()
    total = build_corpus(
        args.inputs, args.tokenizer, args.out, dtype=args.dtype,
        separator_id=args.separator_id, engine=args.engine,
    )
    print(f"wrote {total} tokens ({args.dtype}) to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
