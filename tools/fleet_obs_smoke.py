"""Fleet-plane observability smoke (round 15, seconds on CPU).

Three lanes over stub-model engines (the fleet machinery is
model-agnostic — tests/test_fleet.py owns the llama exactness tiers):

  1. **local drive** — ``serve_fleet_local`` with journeys + the
     decision log + an SLO: the journey dump and the audit log must
     validate against the golden-pinned schemas, every journey's delay
     attribution must reconcile with its result's latency, and the
     route decisions must carry their load evidence;
  2. **kill drill** — a 3-replica live ``ServeFleet``, one replica
     hard-killed mid-decode: zero requests lost, and every drained
     request's journey stitches dead-replica spans to survivor spans
     validator-clean (seam conservation included), with the
     death/drain/re-route audit trail present;
  3. **federation** — the fleet_* rollups land in the registry and the
     Prometheus exposition renders them.

Dumps land in /tmp/nexus_fleet_obs_smoke for
``tools/trace_summary.py`` to render (both renderers are exercised
here so a schema change that breaks the tooling fails the smoke, not a
user).

Run: ``make fleet-obs-smoke`` (CI fast job) or
``JAX_PLATFORMS=cpu python tools/fleet_obs_smoke.py``.
"""

import json
import os
import sys
import threading
import time
from types import SimpleNamespace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT_DIR = "/tmp/nexus_fleet_obs_smoke"


def _stub_cfg_fwd(v=13):
    import jax
    import jax.numpy as jnp

    cfg = SimpleNamespace(
        n_layers=1, n_kv_heads=1, head_dim=8, dtype=jnp.float32,
        max_seq_len=256, vocab_size=v,
    )

    def fwd(params, cfg_, tokens, cache):
        logits = jax.nn.one_hot((tokens + 1) % v, v) * 10.0
        new = {k: x for k, x in cache.items() if k != "n_valid"}
        nv = cache.get("n_valid")
        adv = tokens.shape[1] if nv is None else nv
        new["length"] = cache["length"] + adv
        return logits.astype(jnp.float32), new

    return cfg, fwd


def _queue(v=13, families=5, per_family=3, budget=24):
    from nexus_tpu.runtime.serving import ServeRequest

    reqs = []
    for f in range(families):
        preamble = [(f * 2 + 1) % v] * 16
        for i in range(per_family):
            reqs.append(ServeRequest(
                prompt=preamble + [(i + 1) % v], max_new_tokens=budget,
            ))
    return reqs


def _expected(req, v=13):
    out = [int(t) for t in req.prompt]
    cur = out[-1]
    for _ in range(req.max_new_tokens):
        cur = (cur + 1) % v
        out.append(cur)
    return out


def check(ok, msg):
    if not ok:
        print(f"FAIL: {msg}")
        sys.exit(1)
    print(f"ok: {msg}")


def lane_local_drive():
    from nexus_tpu.fleet import PrefixAffinityRouter, serve_fleet_local
    from nexus_tpu.obs import (
        journey_attribution,
        validate_fleet_log,
        validate_journey,
    )
    from nexus_tpu.runtime.serving import ServingEngine

    cfg, fwd = _stub_cfg_fwd()
    engines = {
        f"r{i}": ServingEngine(
            fwd, {}, cfg, batch_size=2, max_len=128, chunk=4,
            kv_block_size=8, gauge_tags=[f"engine:r{i}"],
        )
        for i in range(3)
    }
    router = PrefixAffinityRouter(
        list(engines), block_size=8, affinity_depth=2,
    )
    reqs = _queue()
    results, m = serve_fleet_local(engines, router, reqs, slo_s=60.0)
    check(all(r is not None for r in results), "local drive served all")
    check(
        all(res.tokens == _expected(req)
            for req, res in zip(reqs, results)),
        "local drive exact (journeys never perturb tokens)",
    )
    jd, fl = m["journeys"], m["fleet_decision_log"]
    check(validate_journey(jd) == [], "journey dump validates")
    check(validate_fleet_log(fl) == [], "decision log validates")
    routes = [e for e in fl["events"] if e["kind"] == "route"]
    check(len(routes) == len(reqs), "one route decision per request")
    check(
        all(len(e["loads"]) == len(e["ranked"]) for e in routes),
        "route decisions carry per-candidate load evidence",
    )
    by_req = {rec["request"]: rec for rec in jd["journeys"]}
    drift = [
        abs(journey_attribution(by_req[i])["latency_s"] - r.latency_s)
        for i, r in enumerate(results)
    ]
    check(max(drift) < 1e-3,
          "journey delay attribution reconciles with result latency")
    check(m["fleet_slo_attainment"] == 1.0, "SLO rollup present")
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(os.path.join(OUT_DIR, "journeys.json"), "w") as f:
        json.dump(jd, f, indent=1)
    with open(os.path.join(OUT_DIR, "fleet_log.json"), "w") as f:
        json.dump(fl, f, indent=1)
    return jd


def lane_kill_drill():
    from nexus_tpu.cluster.store import ClusterStore
    from nexus_tpu.api.types import ConfigMap
    from nexus_tpu.cluster.store import NotFoundError
    from nexus_tpu.fleet import PrefixAffinityRouter, ServeFleet
    from nexus_tpu.ha.lease import heartbeat_name
    from nexus_tpu.ha.serve_failover import serve_replica_template
    from nexus_tpu.obs import validate_fleet_log, validate_journey
    from nexus_tpu.runtime.serving import ServingEngine

    cfg, fwd = _stub_cfg_fwd()

    def make_engine(rid):
        return ServingEngine(
            fwd, {}, cfg, batch_size=2, max_len=128, chunk=4,
            kv_block_size=8, gauge_tags=[f"engine:{rid}"],
        )

    store = ClusterStore("fleet-obs-smoke")
    router = PrefixAffinityRouter([], block_size=8, affinity_depth=2)
    fleet = ServeFleet(
        make_engine, store, "smoke", "fo", replicas=3, router=router,
        ttl_seconds=0.3, pace_s=0.012, slo_s=60.0,
    )
    reqs = _queue(families=6, per_family=3, budget=100)
    fired = threading.Lock()
    victim = [None]

    def kill_once(rid):
        if fired.acquire(blocking=False):
            victim[0] = rid
            fleet.kill_replica(rid, hard=True)

    def watch(rid):
        name = heartbeat_name(serve_replica_template("fo", rid))
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                store.get(ConfigMap.KIND, "smoke", name)
            except NotFoundError:
                time.sleep(0.005)
                continue
            time.sleep(0.1)
            kill_once(rid)
            return

    for rid in ("r0", "r1", "r2"):
        threading.Thread(target=watch, args=(rid,), daemon=True).start()
    results, report = fleet.run(reqs, timeout_s=120)
    check(report["requests_lost"] == 0, "kill drill: zero requests lost")
    check(report["deaths"] == 1, "kill drill: one confirmed death")
    jd, fl = report["journeys"], report["fleet_decision_log"]
    check(validate_journey(jd) == [],
          "kill drill: stitched journeys validate (seams conserve "
          "committed tokens)")
    check(validate_fleet_log(fl) == [], "kill drill: audit log validates")
    stitched = [rec for rec in jd["journeys"] if len(rec["legs"]) > 1]
    check(bool(stitched), "kill drill: cross-replica journeys present")
    check(
        all(rec["legs"][0]["replica"] == victim[0]
            and rec["legs"][-1]["replica"] != victim[0]
            for rec in stitched),
        "kill drill: dead-replica legs hand off to survivors",
    )
    kinds = {e["kind"] for e in fl["events"]}
    check({"death_confirmed", "drain", "route", "spawn"} <= kinds,
          "kill drill: death/drain/route audit trail present")
    check("slo" in report and report["slo"]["slo_attainment"] > 0,
          "kill drill: goodput-under-SLO reported")
    with open(os.path.join(OUT_DIR, "kill_journeys.json"), "w") as f:
        json.dump(jd, f, indent=1)
    with open(os.path.join(OUT_DIR, "kill_fleet_log.json"), "w") as f:
        json.dump(fl, f, indent=1)


def lane_federation():
    from nexus_tpu.obs import render_prometheus
    from nexus_tpu.obs.federation import fleet_rollup
    from nexus_tpu.utils.telemetry import (
        METRIC_FLEET_QUEUE_DEPTH,
        METRIC_SERVE_QUEUE_DEPTH,
        get_client,
    )

    client = get_client()
    # the engines of the earlier lanes published tagged gauges into the
    # process registry; roll them up and render
    rollup = fleet_rollup(["r0", "r1", "r2"], client=client)
    check("fleet_replicas_alive" in rollup, "fleet rollup computes")
    check(METRIC_FLEET_QUEUE_DEPTH in rollup
          or client.get_tagged(METRIC_SERVE_QUEUE_DEPTH,
                               ["engine:r0"]) is None,
          "rollup sums published per-replica gauges")
    text = render_prometheus(client)
    check("serve_queue_depth" in text, "exposition renders serve gauges")
    check("fleet_" in text or "fleet_queue_depth_total" not in rollup,
          "exposition renders fleet gauges when published")


def lane_render():
    import subprocess

    for name in ("journeys.json", "kill_journeys.json",
                 "kill_fleet_log.json"):
        path = os.path.join(OUT_DIR, name)
        out = subprocess.run(
            [sys.executable, "tools/trace_summary.py", path],
            capture_output=True, text=True, timeout=60,
        )
        check(out.returncode == 0 and out.stdout.strip(),
              f"trace_summary renders {name}")


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    lane_local_drive()
    lane_kill_drill()
    lane_federation()
    lane_render()
    print(f"fleet-obs smoke PASSED (dumps in {OUT_DIR})")


if __name__ == "__main__":
    main()
