"""NX-LOCK — ``guarded-by`` lock discipline.

The store/informer/workqueue trio is the concurrency backbone of the
control plane: every cache, queue, and watch-event buffer in them is
documented as "accessed under ``_lock``" (or ``_cond``), and the
``race-smoke`` harness hammers exactly that contract. Comments don't
compile, though — a new method reading ``self._items`` without the lock
passes every deterministic test and corrupts state only under the
parallel shard fan-out. This family makes the comment checkable, the
poor-Python's cousin of Go's ``go vet``-adjacent guarded-by analyses
and Clang's ``GUARDED_BY`` thread-safety annotations.

Annotation grammar (see docs/static-analysis.md):

  * attribute: a trailing comment on its ``__init__`` assignment::

        self._items: Dict[str, APIObject] = {}  # guarded-by: _lock

  * method precondition (caller must hold the lock; the body is then
    checked as if inside it)::

        def _bucket(self, kind, namespace):  # guarded-by: _lock

Rules:

  NX-LOCK001  guarded attribute read/written outside ``with self.<lock>``
              (``__init__`` is exempt: construction happens-before
              publication)
  NX-LOCK002  annotation names a lock attribute the class never assigns
              (typo guard — a misspelled lock silently guards nothing)

Condition objects count as their own lock (``with self._cond:``), which
is how the workqueue's dirty/processing sets are annotated.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from tools.nexuslint.core import FileContext, Finding, rule

_GUARDED_RE = re.compile(r"guarded-by:\s*(?:self\.)?(\w+)")


def _self_attr(node: ast.AST):
    """-> attribute name for ``self.<name>`` nodes, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _annotation_on(ctx: FileContext, node: ast.AST, def_line_only: bool = False):
    """``def_line_only`` is set for method preconditions: a FunctionDef's
    end_lineno is its LAST body line, and honoring a guarded-by comment
    there would silently mark the whole method as a lock holder (turning
    the rule OFF for it) whenever its final statement carries an
    attribute-style annotation."""
    lines = {node.lineno}
    if not def_line_only:
        lines.add(getattr(node, "end_lineno", node.lineno))
    for line in lines:
        m = _GUARDED_RE.search(ctx.comment_on(line))
        if m:
            return m.group(1)
    return None


def _class_info(ctx: FileContext, cls: ast.ClassDef):
    """-> (guarded {attr: lock}, holder methods {name: lock},
    lock-ish attrs assigned in __init__)."""
    guarded: Dict[str, str] = {}
    holders: Dict[str, str] = {}
    init_attrs: Set[str] = set()
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        lock = _annotation_on(ctx, item, def_line_only=True)
        if lock and item.name != "__init__":
            holders[item.name] = lock
        if item.name != "__init__":
            continue
        for node in ast.walk(item):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                init_attrs.add(attr)
                lock = _annotation_on(ctx, node)
                if lock:
                    guarded[attr] = lock
    return guarded, holders, init_attrs


def _with_locks(node: ast.With) -> Set[str]:
    out: Set[str] = set()
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr:
            out.add(attr)
    return out


def _check_method(
    ctx: FileContext,
    method: ast.FunctionDef,
    guarded: Dict[str, str],
    held0: Set[str],
) -> List[Finding]:
    findings: List[Finding] = []
    # (node, held-locks) worklist preserving lexical lock scope
    stack: List[Tuple[ast.AST, Set[str]]] = [(method, held0)]
    while stack:
        node, held = stack.pop()
        for child in ast.iter_child_nodes(node):
            attr = _self_attr(child)
            if attr is not None and attr in guarded and guarded[attr] not in held:
                findings.append(Finding(
                    "NX-LOCK001", ctx.path, child.lineno, child.col_offset,
                    f"self.{attr} is guarded-by {guarded[attr]} but accessed "
                    f"outside `with self.{guarded[attr]}` in {method.name}()",
                ))
                continue  # don't re-flag the nested Name('self')
            if isinstance(child, ast.With):
                stack.append((child, held | _with_locks(child)))
            else:
                stack.append((child, held))
    return findings


@rule("NX-LOCK001", "guarded-by attribute accessed outside its lock")
def check_guarded_access(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded, holders, _ = _class_info(ctx, cls)
        if not guarded:
            continue
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue
            held0 = {holders[item.name]} if item.name in holders else set()
            out.extend(_check_method(ctx, item, guarded, held0))
    return out


@rule("NX-LOCK002", "guarded-by annotation names a lock the class never assigns")
def check_guard_lock_exists(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded, holders, init_attrs = _class_info(ctx, cls)
        named = set(guarded.values()) | set(holders.values())
        for lock in sorted(named):
            if lock not in init_attrs:
                out.append(Finding(
                    "NX-LOCK002", ctx.path, cls.lineno, cls.col_offset,
                    f"guarded-by annotation in class {cls.name} names "
                    f"{lock!r}, which __init__ never assigns",
                ))
    return out
