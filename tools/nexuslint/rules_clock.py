"""NX-CLOCK — clock discipline.

The failure-detection and serving planes are built around **injectable
clocks** (``FailureDetector(clock=...)``, ``ServingEngine(clock=...)``):
every deadline, TTL, and latency path unit-tests in milliseconds with a
fake clock, and wall-clock skew can never leak into protocol decisions.
One stray ``time.monotonic()`` in such a module silently splits time into
two sources — the injected clock the tests control and the real one they
don't — which is exactly how flaky timing tests and untestable deadline
paths are born.

A module is **clock-disciplined** when either:

  * it matches the ``[rule:NX-CLOCK] include`` list in ``nexuslint.ini``
    (the repo pins its known disciplined modules there), or
  * any function in it takes a parameter named ``clock`` or ``sleep``
    (auto-detection — a module that OFFERS injection must also USE it).

Inside a disciplined module, rules:

  NX-CLOCK001  direct wall-clock read: ``time.time()`` /
               ``time.monotonic()`` / ``time.perf_counter()`` (and _ns
               variants) / ``datetime.now()`` / ``datetime.utcnow()``
  NX-CLOCK002  direct ``time.sleep()`` (inject a sleeper / pace hook)

A third discipline (PR 12) covers MONOTONIC-ONLY zones — modules whose
timestamps must subtract cleanly (span timelines, flight-recorder
events): wall clocks there are not merely untestable, they make
*timelines lie* across NTP steps and DST. Files matching the
``[rule:NX-CLOCK] monotonic_only`` globs in ``nexuslint.ini`` (the repo
pins ``nexus_tpu/obs/*``) get:

  NX-CLOCK003  wall-clock read (``time.time[_ns]()`` /
               ``datetime.now()`` / ``utcnow()`` / ``today()``) in a
               monotonic-only module; ``time.monotonic()`` and
               ``perf_counter()`` remain legal there (they ARE the
               monotonic family — though the obs modules themselves
               take engine-stamped timestamps and read no clock at
               all, which rules 001/002 separately enforce wherever a
               ``clock`` parameter appears).

References (not calls) stay legal — ``clock: Callable = time.monotonic``
as a default value IS the injection idiom. Deliberately-informational
wall stamps (e.g. a lease's ``renewTime``, never compared by anyone) are
suppressed at the site with a justification comment.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import Dict, List

from tools.nexuslint.core import (
    FileContext,
    Finding,
    all_args,
    dotted_name,
    rule,
    walk_functions,
)

_TIME_FUNCS = {
    "time", "monotonic", "monotonic_ns", "perf_counter", "perf_counter_ns",
}
_DT_FUNCS = {"now", "utcnow"}
_INJECT_PARAMS = {"clock", "sleep"}
# the WALL-clock subset (NX-CLOCK003): reads whose epoch can step under
# NTP/DST — banned outright in monotonic-only zones, where timestamps
# exist to be subtracted
_WALL_TIME_FUNCS = {"time", "time_ns"}
_WALL_DT_FUNCS = {"now", "utcnow", "today"}


def _alias_maps(tree: ast.Module):
    """(module aliases {local: canonical}, from-imports {local: 'mod.fn'})."""
    mods: Dict[str, str] = {}
    funcs: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("time", "datetime"):
                    mods[a.asname or a.name] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module in ("time", "datetime"):
            for a in node.names:
                funcs[a.asname or a.name] = f"{node.module}.{a.name}"
    return mods, funcs


def _is_disciplined(ctx: FileContext) -> bool:
    if ctx.config.family_includes("NX-CLOCK", ctx.path):
        return True
    for fn in walk_functions(ctx.tree):
        for a in all_args(fn):
            if a.arg in _INJECT_PARAMS:
                return True
    return False


def _classify_call(call: ast.Call, mods, funcs):
    """-> ('read'|'sleep', canonical name) for banned calls, else None."""
    name = dotted_name(call.func)
    if name is None:
        return None
    parts = name.split(".")
    # from-import alias: monotonic() / sleep() / now-bound datetime class
    if parts[0] in funcs:
        parts = funcs[parts[0]].split(".") + parts[1:]
    # module alias: t.monotonic() -> time.monotonic()
    if parts[0] in mods:
        parts = [mods[parts[0]]] + parts[1:]
    canonical = ".".join(parts)
    if parts[0] == "time" and len(parts) == 2:
        if parts[1] == "sleep":
            return "sleep", canonical
        if parts[1] in _TIME_FUNCS:
            return "read", canonical
    # datetime.datetime.now() / datetime.now() (class imported directly)
    if parts[0] == "datetime" and parts[-1] in _DT_FUNCS and len(parts) <= 3:
        return "read", canonical
    return None


@rule("NX-CLOCK001", "direct wall-clock read in a clock-disciplined module")
def check_clock_reads(ctx: FileContext) -> List[Finding]:
    if not _is_disciplined(ctx):
        return []
    mods, funcs = _alias_maps(ctx.tree)
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        hit = _classify_call(node, mods, funcs)
        if hit and hit[0] == "read":
            out.append(Finding(
                "NX-CLOCK001", ctx.path, node.lineno, node.col_offset,
                f"direct {hit[1]}() in a clock-disciplined module; "
                "route it through the injectable clock",
            ))
    return out


def _monotonic_only_scope(ctx: FileContext) -> bool:
    """Is this file in the ``monotonic_only`` globs of nexuslint.ini?"""
    raw = ctx.config.option("NX-CLOCK", "monotonic_only", "")
    pats = [x.strip() for x in re.split(r"[,\n]", raw) if x.strip()]
    for pat in pats:
        if (fnmatch.fnmatch(ctx.path, pat)
                or fnmatch.fnmatch(os.path.basename(ctx.path), pat)):
            return True
    return False


def _classify_wall_call(call: ast.Call, mods, funcs):
    """canonical name for WALL-clock reads (the NX-CLOCK003 ban set:
    epoch-stepping reads only — the monotonic family stays legal)."""
    name = dotted_name(call.func)
    if name is None:
        return None
    parts = name.split(".")
    if parts[0] in funcs:
        parts = funcs[parts[0]].split(".") + parts[1:]
    if parts[0] in mods:
        parts = [mods[parts[0]]] + parts[1:]
    canonical = ".".join(parts)
    if parts[0] == "time" and len(parts) == 2 and parts[1] in _WALL_TIME_FUNCS:
        return canonical
    if (parts[0] == "datetime" and parts[-1] in _WALL_DT_FUNCS
            and len(parts) <= 3):
        return canonical
    return None


@rule("NX-CLOCK003", "wall-clock read in a monotonic-only module")
def check_monotonic_only(ctx: FileContext) -> List[Finding]:
    if not _monotonic_only_scope(ctx):
        return []
    mods, funcs = _alias_maps(ctx.tree)
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        hit = _classify_wall_call(node, mods, funcs)
        if hit:
            out.append(Finding(
                "NX-CLOCK003", ctx.path, node.lineno, node.col_offset,
                f"wall-clock {hit}() in a monotonic-only module; span "
                "and flight-recorder timestamps must subtract cleanly "
                "— use the engine-stamped monotonic t instead",
            ))
    return out


@rule("NX-CLOCK002", "direct time.sleep in a clock-disciplined module")
def check_clock_sleeps(ctx: FileContext) -> List[Finding]:
    if not _is_disciplined(ctx):
        return []
    mods, funcs = _alias_maps(ctx.tree)
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        hit = _classify_call(node, mods, funcs)
        if hit and hit[0] == "sleep":
            out.append(Finding(
                "NX-CLOCK002", ctx.path, node.lineno, node.col_offset,
                f"direct {hit[1]}() in a clock-disciplined module; "
                "inject a sleeper (the supervisor/launcher pace pattern)",
            ))
    return out
