"""NX-PAIR — exception-safe resource acquire/release pairing.

The expensive leaks in this stack are not file handles — they are KV
blocks (``BlockAllocator.admit`` → ``lease.release``: a leaked lease
permanently shrinks the serve pool), heartbeat/election leases, chaos
hooks left installed across tests, and watch subscriptions. All of them
follow the same shape: an acquire call whose paired release must run on
EVERY exit path, which in Python means a ``finally`` block or a context
manager — a bare ``acquire(); ...; release()`` sequence leaks the moment
anything between them raises.

The pair table lives in ``nexuslint.ini`` (``[rule:NX-PAIR] pairs``),
one ``acquire:release`` entry per resource kind; either side may be
qualified with a receiver hint (``chaos.add:chaos.clear`` only matches
calls whose receiver chain ends in ``chaos``).

  NX-PAIR001  a function contains both an acquire site and its paired
              release site, but no release is inside a ``finally`` block
              and the acquire is not used as a context manager

Functions that only acquire (handing the lease to a caller or storing it
on ``self``) are intentionally NOT flagged — ownership transfer is the
allocator's normal protocol; the rule targets the local
acquire-use-release shape where exception safety is the author's job.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import List, Optional, Tuple

from tools.nexuslint.core import FileContext, Finding, dotted_name, rule

DEFAULT_PAIRS = (
    "admit:release, acquire:release, try_acquire:release, grow_to:release, "
    "chaos.add:chaos.clear, subscribe:unsubscribe, "
    "index.insert:index.remove, index.spill:index.restore"
)


@dataclass(frozen=True)
class _Side:
    method: str
    receiver: Optional[str]  # last receiver component, or None = any

    @classmethod
    def parse(cls, spec: str) -> "_Side":
        parts = spec.strip().split(".")
        if len(parts) == 1:
            return cls(parts[0], None)
        return cls(parts[-1], parts[-2])

    def matches(self, call: ast.Call) -> bool:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            if fn.attr != self.method:
                return False
            if self.receiver is None:
                return True
            recv = dotted_name(fn.value)
            return recv is not None and recv.split(".")[-1] == self.receiver
        if isinstance(fn, ast.Name):
            return self.receiver is None and fn.id == self.method
        return False


def _pairs(ctx: FileContext) -> List[Tuple[_Side, _Side]]:
    raw = ctx.config.option("NX-PAIR", "pairs", DEFAULT_PAIRS)
    out: List[Tuple[_Side, _Side]] = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry or ":" not in entry:
            continue
        acq, rel = entry.split(":", 1)
        out.append((_Side.parse(acq), _Side.parse(rel)))
    return out


def _own_nodes(fn: ast.AST):
    """Walk a function's body WITHOUT descending into nested defs (each
    nested function is its own pairing scope)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _finally_calls(fn: ast.AST):
    """Call nodes located inside any finally block of this function."""
    out = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        out.add(id(sub))
    return out


def _with_context_calls(fn: ast.AST):
    """Call nodes used as `with` context expressions (ctx-manager acquire)."""
    out = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        out.add(id(sub))
    return out


@rule("NX-PAIR001", "acquire whose paired release is not exception-safe")
def check_pairing(ctx: FileContext) -> List[Finding]:
    pairs = _pairs(ctx)
    if not pairs:
        return []
    out: List[Finding] = []
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        calls = [n for n in _own_nodes(fn) if isinstance(n, ast.Call)]
        if not calls:
            continue
        in_finally = _finally_calls(fn)
        in_with = _with_context_calls(fn)
        for acq_side, rel_side in pairs:
            acquires = [c for c in calls if acq_side.matches(c)]
            releases = [c for c in calls if rel_side.matches(c)]
            if not acquires or not releases:
                continue  # pure acquire (ownership transfer) or pure release
            if any(id(c) in in_finally for c in releases):
                continue
            for acq in acquires:
                if id(acq) in in_with:
                    continue
                out.append(Finding(
                    "NX-PAIR001", ctx.path, acq.lineno, acq.col_offset,
                    f"{acq_side.method}() is released by "
                    f"{rel_side.method}() in {fn.name}() but no release is "
                    "in a finally block — an exception between them leaks "
                    "the resource",
                ))
    return out
