"""nexuslint — project-invariant static analysis for nexus-tpu.

Generic linters protect generic invariants. This repo's load-bearing
conventions — injectable clocks in the failure-detection and serving
planes, ``guarded-by`` lock discipline in the store/informer/workqueue,
JAX trace purity inside jitted programs, and exception-safe pairing of
resource acquire/release sites — are enforced by nothing a stock tool
knows about. nexuslint is the AST-based rule suite that closes that gap
(the Python answer to the race detector + vet lineage the reference Go
controller inherits for free).

Usage (repo root)::

    python -m tools.nexuslint [paths...]          # full rule set
    python -m tools.nexuslint --select NX-IMP .   # one family
    python -m tools.nexuslint --list-rules

Rule families (docs/static-analysis.md has the full catalogue):

  NX-CLOCK  clock discipline   — no direct wall-clock reads / sleeps in
                                 modules that take an injectable clock
  NX-LOCK   lock discipline    — ``# guarded-by: <lock>`` attributes
                                 accessed only under ``with self.<lock>``
  NX-JIT    JAX trace purity   — no host materialization, numpy RNG, or
                                 mutable defaults inside jitted programs
  NX-PAIR   resource pairing   — acquire sites whose paired release is
                                 not exception-safe (``finally``/ctx mgr)
  NX-IMP    import hygiene     — unused imports (the ruff-F401 fallback
                                 for environments without ruff)

Per-line suppression: trailing ``# nexuslint: disable=NX-JIT001`` (or a
comma list, or ``disable=all``); file-level: a leading-comment line
``# nexuslint: disable-file=NX-CLOCK001``. Scoping lives in
``nexuslint.ini`` at the repo root.
"""

from tools.nexuslint.core import (  # noqa: F401
    Finding,
    LintConfig,
    Rule,
    iter_rules,
    lint_paths,
    lint_source,
    load_config,
    rule,
)

# import for side effect: each module registers its rules
from tools.nexuslint import (  # noqa: E402,F401
    rules_clock,
    rules_imports,
    rules_jit,
    rules_locks,
    rules_pairing,
)

__version__ = "1.0.0"
