"""NX-IMP — import hygiene (the ruff fallback).

CI lints with ruff, but the repo must also gate in environments where
ruff isn't installable (the TPU containers bake a fixed toolchain). This
family reimplements the highest-value subset — unused imports, ruff's
F401 — with stdlib ``ast`` so ``make lint`` can NEVER silently degrade
to a no-op again (the ``ruff check || true`` failure mode this PR
removes).

  NX-IMP001  imported name never used in the module

Deliberately conservative, mirroring ruff's own carve-outs:

  * ``__init__.py`` files are skipped (re-export surface);
  * ``from x import y as y`` (self-alias) marks an intentional re-export;
  * imports under ``try:`` are skipped (availability probes);
  * a ``# noqa`` on the import line is honored (ruff compatibility), as
    is the native ``# nexuslint: disable=NX-IMP001``;
  * names in ``__all__`` count as used.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from tools.nexuslint.core import FileContext, Finding, rule

_NOQA_RE = re.compile(r"noqa(?::\s*[\w, ]+)?\b", re.IGNORECASE)


def _used_names(tree: ast.Module) -> Set[str]:
    used: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and not isinstance(node.ctx, ast.Store):
            used.add(node.id)
        elif isinstance(node, ast.Assign):
            # __all__ = ["x", ...] marks its entries as used
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, str
                        ):
                            used.add(sub.value)
    return used


def _in_try(tree: ast.Module) -> Set[int]:
    """ids of import statements nested under any try block."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            for stmt in ast.walk(node):
                if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                    out.add(id(stmt))
    return out


@rule("NX-IMP001", "imported name is never used")
def check_unused_imports(ctx: FileContext) -> List[Finding]:
    if ctx.path.endswith("__init__.py"):
        return []
    used = _used_names(ctx.tree)
    guarded = _in_try(ctx.tree)
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if id(node) in guarded:
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        if _NOQA_RE.search(ctx.comment_on(node.lineno)) or _NOQA_RE.search(
            ctx.comment_on(getattr(node, "end_lineno", node.lineno))
        ):
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            if alias.asname is not None and alias.asname == alias.name:
                continue  # explicit re-export (from x import y as y)
            bound = alias.asname or alias.name.split(".")[0]
            if bound not in used:
                out.append(Finding(
                    "NX-IMP001", ctx.path, node.lineno, node.col_offset,
                    f"{alias.asname or alias.name!s} imported but unused",
                ))
    return out
