"""CLI: ``python -m tools.nexuslint [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/config error — so ``make
analyze`` and CI gate on it directly.
"""

from __future__ import annotations

import argparse
import os
import sys

import tools.nexuslint as nexuslint
from tools.nexuslint.core import _selected, iter_rules, lint_paths, load_config

DEFAULT_CONFIG = "nexuslint.ini"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nexuslint",
        description="project-invariant static analysis for nexus-tpu",
    )
    ap.add_argument("paths", nargs="*", default=[], help="files or trees (default: nexus_tpu)")
    ap.add_argument(
        "--select", action="append", default=None, metavar="RULE",
        help="only run rules whose id starts with RULE (repeatable), "
        "e.g. --select NX-IMP or --select NX-JIT002",
    )
    ap.add_argument(
        "--config", default=None,
        help=f"config file (default: ./{DEFAULT_CONFIG} when present)",
    )
    ap.add_argument("--list-rules", action="store_true", help="print the rule catalogue")
    ap.add_argument("-q", "--quiet", action="store_true", help="findings only, no summary")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in iter_rules():
            print(f"{r.id:12s} {r.summary}")
        return 0

    config_path = args.config
    if config_path is None and os.path.exists(DEFAULT_CONFIG):
        config_path = DEFAULT_CONFIG
    if config_path is not None and not os.path.exists(config_path):
        print(f"nexuslint: config not found: {config_path}", file=sys.stderr)
        return 2
    config = load_config(config_path)

    paths = args.paths or ["nexus_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print(f"nexuslint: no such path: {p}", file=sys.stderr)
            return 2

    findings = lint_paths(paths, config, select=args.select)
    for f in findings:
        print(f.format())
    if not args.quiet:
        n_rules = len([r for r in iter_rules() if _selected(r, args.select)])
        tag = f"nexuslint {nexuslint.__version__}"
        if findings:
            print(f"{tag}: {len(findings)} finding(s) [{n_rules} rules]",
                  file=sys.stderr)
        else:
            print(f"{tag}: clean [{n_rules} rules]", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
