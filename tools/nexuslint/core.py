"""nexuslint core: rule registry, config, suppressions, file runner.

Design goals, in order:

  1. **Zero dependencies** — stdlib ``ast`` + ``tokenize`` + ``configparser``
     only, so the gate runs in every environment the repo runs in
     (including containers without ruff).
  2. **Project-scoped precision** — rules key off THIS repo's annotations
     and conventions (``guarded-by`` comments, injectable ``clock``
     parameters, ``jax.jit`` factories), so a finding is an invariant
     violation, not a style nit.
  3. **Escape hatches that leave a paper trail** — per-line
     ``# nexuslint: disable=<rule>`` and per-file/per-rule ``nexuslint.ini``
     scoping, so a deliberate exception is visible at the site it excuses.
"""

from __future__ import annotations

import ast
import configparser
import fnmatch
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# findings


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule_id: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}"


# ---------------------------------------------------------------------------
# config


@dataclass
class LintConfig:
    """Parsed ``nexuslint.ini``.

    ``exclude``: repo-relative glob patterns never linted at all.
    ``rule_include`` / ``rule_exclude``: per-FAMILY path scoping — when a
    family has an ``include`` list, only matching files are checked by that
    family's auto-detection-independent rules; ``exclude`` always wins.
    ``options``: per-family free-form key/value options (e.g. the pairing
    rule's acquire:release table).
    """

    exclude: List[str] = field(default_factory=list)
    rule_include: Dict[str, List[str]] = field(default_factory=dict)
    rule_exclude: Dict[str, List[str]] = field(default_factory=dict)
    options: Dict[str, Dict[str, str]] = field(default_factory=dict)

    def file_excluded(self, rel_path: str) -> bool:
        return _match_any(rel_path, self.exclude)

    def family_allows(self, family: str, rel_path: str) -> bool:
        """May rules of ``family`` examine this file at all?"""
        if _match_any(rel_path, self.rule_exclude.get(family, [])):
            return False
        return True

    def family_includes(self, family: str, rel_path: str) -> bool:
        """Is this file in the family's explicit ``include`` scope?
        (False also when no include list is configured — rules combine
        this with their own auto-detection.)"""
        return _match_any(rel_path, self.rule_include.get(family, []))

    def option(self, family: str, key: str, default: str = "") -> str:
        return self.options.get(family, {}).get(key, default)


def _match_any(rel_path: str, patterns: Sequence[str]) -> bool:
    p = rel_path.replace(os.sep, "/")
    for pat in patterns:
        if fnmatch.fnmatch(p, pat) or fnmatch.fnmatch(os.path.basename(p), pat):
            return True
    return False


def _split_list(raw: str) -> List[str]:
    return [x.strip() for x in re.split(r"[,\n]", raw) if x.strip()]


def load_config(path: Optional[str] = None) -> LintConfig:
    """Load ``nexuslint.ini`` (missing file → permissive defaults)."""
    cfg = LintConfig()
    if path is None or not os.path.exists(path):
        return cfg
    parser = configparser.ConfigParser()
    parser.read(path)
    if parser.has_section("nexuslint"):
        cfg.exclude = _split_list(parser.get("nexuslint", "exclude", fallback=""))
    for section in parser.sections():
        if not section.startswith("rule:"):
            continue
        family = section[len("rule:"):]
        opts = dict(parser.items(section))
        if "include" in opts:
            cfg.rule_include[family] = _split_list(opts.pop("include"))
        if "exclude" in opts:
            cfg.rule_exclude[family] = _split_list(opts.pop("exclude"))
        cfg.options[family] = opts
    return cfg


# ---------------------------------------------------------------------------
# per-file context shared by every rule


class FileContext:
    """Parsed view of one source file: AST, per-line comments, config."""

    def __init__(self, rel_path: str, source: str, config: LintConfig):
        self.path = rel_path.replace(os.sep, "/")
        self.source = source
        self.config = config
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        #: physical line number -> comment text (without leading '#')
        self.comments: Dict[int, str] = {}
        try:
            self.tree = ast.parse(source)
        except SyntaxError as e:  # surfaced as its own finding
            self.syntax_error = e
            return
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string.lstrip("#").strip()
        except tokenize.TokenError:
            pass  # AST parsed; comments best-effort

    def comment_on(self, line: int) -> str:
        return self.comments.get(line, "")


# ---------------------------------------------------------------------------
# suppressions

_DISABLE_RE = re.compile(r"nexuslint:\s*disable(?P<file>-file)?\s*=\s*(?P<ids>[\w\-, ]+)")


def _parse_disables(comment: str) -> Tuple[Optional[List[str]], Optional[List[str]]]:
    """-> (line_ids, file_ids); an id list of ['all'] disables everything."""
    m = _DISABLE_RE.search(comment)
    if not m:
        return None, None
    ids = [x.strip() for x in m.group("ids").split(",") if x.strip()]
    if m.group("file"):
        return None, ids
    return ids, None


def _suppressed(finding: Finding, ctx: FileContext, file_ids: List[str]) -> bool:
    def covers(ids: Iterable[str]) -> bool:
        for i in ids:
            if i == "all" or finding.rule_id == i or finding.rule_id.startswith(i):
                return True
        return False

    if covers(file_ids):
        return True
    line_ids, _ = _parse_disables(ctx.comment_on(finding.line))
    return bool(line_ids and covers(line_ids))


def _file_disables(ctx: FileContext) -> List[str]:
    out: List[str] = []
    for comment in ctx.comments.values():
        _, file_ids = _parse_disables(comment)
        if file_ids:
            out.extend(file_ids)
    return out


# ---------------------------------------------------------------------------
# rule registry


@dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    check: Callable[[FileContext], List[Finding]]

    @property
    def family(self) -> str:
        return self.id.rstrip("0123456789")


_REGISTRY: Dict[str, Rule] = {}


def rule(rule_id: str, summary: str):
    """Register a rule. The check receives a :class:`FileContext` and
    returns findings; scoping and suppression are handled by the runner."""

    def wrap(fn: Callable[[FileContext], List[Finding]]) -> Rule:
        if rule_id in _REGISTRY:
            raise ValueError(f"duplicate rule id {rule_id}")
        r = Rule(rule_id, summary, fn)
        _REGISTRY[rule_id] = r
        return r

    return wrap


def iter_rules() -> List[Rule]:
    return [r for _, r in sorted(_REGISTRY.items())]


def _selected(r: Rule, select: Optional[Sequence[str]]) -> bool:
    if not select:
        return True
    return any(r.id == s or r.id.startswith(s) or r.family == s for s in select)


# ---------------------------------------------------------------------------
# runners


def lint_source(
    rel_path: str,
    source: str,
    config: Optional[LintConfig] = None,
    select: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint one in-memory source file → surviving findings."""
    config = config or LintConfig()
    ctx = FileContext(rel_path, source, config)
    if ctx.syntax_error is not None:
        e = ctx.syntax_error
        return [
            Finding(
                "NX-SYNTAX", ctx.path, e.lineno or 1, (e.offset or 1) - 1,
                f"file does not parse: {e.msg}",
            )
        ]
    file_ids = _file_disables(ctx)
    findings: List[Finding] = []
    for r in iter_rules():
        if not _selected(r, select):
            continue
        if not config.family_allows(r.family, ctx.path):
            continue
        findings.extend(r.check(ctx))
    findings = [f for f in findings if not _suppressed(f, ctx, file_ids)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    return findings


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in sorted(dirnames)
                    if d not in {"__pycache__", ".git", ".venv", "node_modules"}
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(
    paths: Sequence[str],
    config: Optional[LintConfig] = None,
    select: Optional[Sequence[str]] = None,
    root: Optional[str] = None,
) -> List[Finding]:
    """Lint files/trees → findings (repo-relative paths when under ``root``)."""
    config = config or LintConfig()
    root = os.path.abspath(root or os.getcwd())
    out: List[Finding] = []
    for path in _iter_py_files(paths):
        abs_path = os.path.abspath(path)
        rel = os.path.relpath(abs_path, root)
        if rel.startswith(".."):
            rel = path
        rel = rel.replace(os.sep, "/")
        if config.file_excluded(rel):
            continue
        try:
            with open(abs_path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            out.append(Finding("NX-IO", rel, 1, 0, f"unreadable: {e}"))
            continue
        out.extend(lint_source(rel, source, config, select))
    return out


# ---------------------------------------------------------------------------
# small AST helpers shared by rule modules


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_functions(tree: ast.AST):
    """Yield every FunctionDef/AsyncFunctionDef in the tree."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def all_args(fn) -> List[ast.arg]:
    a = fn.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs] + (
        [a.vararg] if a.vararg else []
    ) + ([a.kwarg] if a.kwarg else [])
