"""NX-JIT — JAX trace purity inside jitted programs.

The serving engine's whole performance premise is "ONE compiled decode
program for any table state" (runtime/serving.py): a host materialization
(``.item()``, ``int(traced)``) inside a jitted function forces a device
sync per call, and a value-dependent Python branch silently turns one
program into one-per-shape — the recompile storm the paged design
exists to avoid. ``np.random`` inside a trace is worse than slow: it
bakes ONE sample into the compiled program, so every subsequent call
replays the same "random" numbers. These are the classic jit footguns
(JAX's own docs call them out), caught here at review time instead of as
a silent 100× serving regression.

What counts as jitted (lexically, including nested defs):

  * decorated: ``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)``,
    ``@functools.partial(jax.jit, ...)``
  * wrapped: ``jax.jit(fn)`` where ``fn`` is a function defined in the
    same module
  * factory-wrapped: ``jax.jit(make_fn(...))`` where ``make_fn`` is a
    local def — its directly nested defs (the returned workers) are
    treated as traced (the ``_make_decode_chunk`` idiom)

Rules:

  NX-JIT001  ``.item()`` on a traced value (host sync per call)
  NX-JIT002  ``int()``/``float()``/``bool()`` cast of a non-static value
             (casts of ``.shape``/``.ndim``/``len()``/constants are
             static and stay legal)
  NX-JIT003  ``np.random.*`` / stdlib ``random.*`` inside a trace
             (baked into the compiled program; use ``jax.random`` keys)
  NX-JIT004  mutable default argument on a jitted function (shared
             across traces — aliasing bugs that only appear on retrace)
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.nexuslint.core import FileContext, Finding, dotted_name, rule

_JIT_NAMES = {"jax.jit", "jit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this decorator/callee expression denote jax.jit?"""
    name = dotted_name(node)
    if name in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        callee = dotted_name(node.func)
        if callee in _PARTIAL_NAMES and node.args:
            return dotted_name(node.args[0]) in _JIT_NAMES
    return False


class _Scope:
    """Lexical scope node: maps names to FunctionDefs defined there."""

    def __init__(self, node: ast.AST, parent: Optional["_Scope"]):
        self.node = node
        self.parent = parent
        self.defs = {}

    def resolve(self, name: str):
        s = self
        while s is not None:
            if name in s.defs:
                return s.defs[name]
            s = s.parent
        return None


def _build_scopes(tree: ast.Module):
    """-> (scope of every function/module node, jit-wrap call sites)."""
    root = _Scope(tree, None)
    scopes = {id(tree): root}
    jit_calls = []  # (Call node, enclosing scope)

    def visit(node: ast.AST, scope: _Scope):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.defs[child.name] = child
                inner = _Scope(child, scope)
                scopes[id(child)] = inner
                visit(child, inner)
            elif isinstance(child, (ast.ClassDef,)):
                # class body is its own namespace but NOT a closure scope;
                # methods resolve names from the enclosing scope
                visit(child, scope)
            else:
                if isinstance(child, ast.Call) and _is_jit_expr(child.func):
                    jit_calls.append((child, scope))
                visit(child, scope)

    visit(tree, root)
    return scopes, jit_calls


def _jitted_functions(tree: ast.Module) -> Set[int]:
    """ids of FunctionDef nodes whose bodies run under jax tracing."""
    traced: Set[int] = set()
    scopes, jit_calls = _build_scopes(tree)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(d) for d in node.decorator_list):
                traced.add(id(node))

    for call, scope in jit_calls:
        if not call.args:
            continue
        target = call.args[0]
        if isinstance(target, ast.Name):
            fn = scope.resolve(target.id)
            if fn is not None:
                traced.add(id(fn))
        elif isinstance(target, ast.Call) and isinstance(target.func, ast.Name):
            factory = scope.resolve(target.func.id)
            if factory is not None:
                # jit(make_fn(...)): the factory's directly nested defs are
                # the returned traced workers
                for child in ast.walk(factory):
                    if (
                        isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and child is not factory
                    ):
                        traced.add(id(child))
        elif isinstance(target, ast.Lambda):
            traced.add(id(target))

    # everything lexically inside a traced function is traced too
    grow = True
    while grow:
        grow = False
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if id(node) not in traced:
                continue
            for child in ast.walk(node):
                if (
                    isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and id(child) not in traced
                ):
                    traced.add(id(child))
                    grow = True
    return traced


def _static_cast_arg(arg: ast.AST) -> bool:
    """Casts of shapes/dims/lengths/constants are trace-static."""
    if isinstance(arg, ast.Constant):
        return True
    for node in ast.walk(arg):
        if isinstance(node, ast.Attribute) and node.attr in (
            "shape", "ndim", "size", "itemsize", "dtype",
        ):
            return True
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee in ("len", "np.dtype"):
                return True
    return False


def _each_traced_body(ctx: FileContext):
    traced = _jitted_functions(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            if id(node) in traced:
                yield node


@rule("NX-JIT001", ".item() host materialization inside a jitted function")
def check_item_calls(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in _each_traced_body(ctx):
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
            ):
                out.append(Finding(
                    "NX-JIT001", ctx.path, node.lineno, node.col_offset,
                    ".item() inside a jitted function forces a host sync "
                    "per call; keep the value on-device",
                ))
    return out


@rule("NX-JIT002", "python scalar cast of a traced value inside a jitted function")
def check_scalar_casts(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in _each_traced_body(ctx):
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                continue
            if node.func.id not in ("int", "float", "bool") or len(node.args) != 1:
                continue
            if _static_cast_arg(node.args[0]):
                continue
            out.append(Finding(
                "NX-JIT002", ctx.path, node.lineno, node.col_offset,
                f"{node.func.id}() cast inside a jitted function "
                "materializes the traced value (ConcretizationError at "
                "best, a silent per-value recompile at worst)",
            ))
    return out


@rule("NX-JIT003", "non-JAX randomness inside a jitted function")
def check_np_random(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in _each_traced_body(ctx):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name.startswith(("np.random.", "numpy.random.", "random.")):
                out.append(Finding(
                    "NX-JIT003", ctx.path, node.lineno, node.col_offset,
                    f"{name}() inside a jitted function bakes ONE sample "
                    "into the compiled program; use jax.random with an "
                    "explicit key",
                ))
    return out


@rule("NX-JIT004", "mutable default argument on a jitted function")
def check_mutable_defaults(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in _each_traced_body(ctx):
        defaults = list(fn.args.defaults) + [
            d for d in fn.args.kw_defaults if d is not None
        ]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and dotted_name(d.func) in ("list", "dict", "set", "bytearray")
            )
            if mutable:
                out.append(Finding(
                    "NX-JIT004", ctx.path, d.lineno, d.col_offset,
                    "mutable default argument on a jitted function is "
                    "shared across traces; use None and allocate inside",
                ))
    return out
