"""Race smoke: hammer one ClusterStore + informer/lister from N threads.

Part of the parallel-fan-out thread-safety audit (see
docs/reconciler-concurrency.md): the reconcile hot path now issues
concurrent per-shard writes from a bounded executor, so the in-process
store, the watch dispatch, and the monotonic lister cache are exercised
here exactly the way the controller exercises them — concurrent
create/update/delete against shared keys, with an informer
subscribed and a second thread doing cache-hot ``_set_if_newer`` writes.

Invariants checked:
  * no exception other than the expected optimistic-concurrency set
    (ConflictError / AlreadyExistsError / NotFoundError) escapes any thread;
  * resourceVersions observed per key through the lister never go backwards
    (the ``_set_if_newer`` monotonicity contract);
  * after the storm quiesces, the lister cache converges to exactly the
    store's surviving objects (no stale entries, no lost deletes).

Exit code 0 = clean, 1 = violation (details printed).

Usage: python tools/race_smoke_store.py [--threads 8] [--seconds 3]
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nexus_tpu.api.types import ObjectMeta, Secret  # noqa: E402
from nexus_tpu.cluster.informer import InformerFactory  # noqa: E402
from nexus_tpu.cluster.store import (  # noqa: E402
    AlreadyExistsError,
    ClusterStore,
    ConflictError,
    NotFoundError,
)

NS = "race"
KEYS = [f"secret-{i}" for i in range(8)]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=3.0)
    args = ap.parse_args(argv)

    store = ClusterStore("race-smoke")
    informers = InformerFactory(store, resync_period=0.05)
    informer = informers.informer(Secret.KIND)
    lister = informer.lister

    # event handlers registered from a side thread WHILE dispatch runs —
    # the registration-vs-dispatch race the informer must tolerate
    dispatched = [0]

    def count(*_a):
        dispatched[0] += 1

    informer.add_event_handler(on_add=count, on_update=count, on_delete=count)
    informers.start()

    stop = threading.Event()
    violations: list = []
    rv_seen: dict = {}
    rv_lock = threading.Lock()

    def check_monotonic(name: str) -> None:
        try:
            obj = lister.get(NS, name)
        except NotFoundError:
            return
        rv = int(obj.metadata.resource_version)
        with rv_lock:
            prev = rv_seen.get(name, 0)
            if rv < prev:
                violations.append(
                    f"lister rv went backwards for {name}: {prev} -> {rv}"
                )
            else:
                rv_seen[name] = rv

    def hammer(seed: int) -> None:
        rng = random.Random(seed)
        while not stop.is_set():
            name = rng.choice(KEYS)
            op = rng.random()
            try:
                if op < 0.25:
                    store.create(
                        Secret(
                            metadata=ObjectMeta(name=name, namespace=NS),
                            data={"v": str(rng.random())},
                        )
                    )
                elif op < 0.70:
                    obj = store.get(Secret.KIND, NS, name)
                    obj.data = {"v": str(rng.random())}
                    store.update(obj)
                elif op < 0.80:
                    store.delete(Secret.KIND, NS, name)
                elif op < 0.90:
                    # cache-hot write racing the watch thread — the
                    # controller's post-write _set_if_newer pattern
                    obj = store.get(Secret.KIND, NS, name)
                    lister._set_if_newer(obj)
                else:
                    store.list(Secret.KIND, NS)
                check_monotonic(name)
            except (ConflictError, AlreadyExistsError, NotFoundError, KeyError):
                pass  # expected optimistic-concurrency outcomes
            except Exception as e:  # noqa: BLE001 — the smoke's whole point
                violations.append(f"unexpected {type(e).__name__}: {e}")
                return

    # late-registration thread: keeps adding handlers mid-storm
    def register_loop() -> None:
        while not stop.is_set():
            informer.add_event_handler(on_update=count)
            time.sleep(0.05)

    threads = [
        threading.Thread(target=hammer, args=(i,), daemon=True)
        for i in range(args.threads)
    ] + [threading.Thread(target=register_loop, daemon=True)]
    for t in threads:
        t.start()
    time.sleep(args.seconds)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)

    # quiesce: let the watch queue drain, then compare cache vs store
    time.sleep(0.3)
    informers.stop()
    store_names = {
        o.metadata.name for o in store.list(Secret.KIND, NS)
    }
    cache_names = {o.metadata.name for o in lister.list(NS)}
    if store_names != cache_names:
        violations.append(
            "lister diverged from store: cache-only="
            f"{sorted(cache_names - store_names)} "
            f"store-only={sorted(store_names - cache_names)}"
        )

    if violations:
        print("RACE SMOKE FAILED:")
        for v in violations[:20]:
            print(f"  - {v}")
        return 1
    print(
        f"race smoke clean: {args.threads} threads x {args.seconds}s, "
        f"{dispatched[0]} events dispatched, "
        f"{len(store_names)} objects surviving"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
