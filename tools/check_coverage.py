"""Per-file / per-package / total coverage gate.

The reference gates coverage at three granularities — file 70, package
70, total 75 (`/root/reference/.testcoverage.yml:5-8`) — so a new
low-coverage module can't hide under a healthy aggregate. This is the
same gate for the pytest-cov JSON report:

    python -m pytest --cov=nexus_tpu --cov-report=json:coverage.json ...
    python tools/check_coverage.py coverage.json

Exit code 1 lists every violation. Exclusions mirror the reference's
(its `pkg/signals` carve-out → `utils/signals.py`: OS signal handlers
whose delivery paths a unit test can't reach deterministically).
"""

from __future__ import annotations

import json
import os
import re
import sys

FILE_THRESHOLD = 70.0
PACKAGE_THRESHOLD = 70.0
TOTAL_THRESHOLD = 75.0

# regexes on repo-relative paths, mirroring the reference's exclude list
EXCLUDE = [
    r"nexus_tpu/utils/signals\.py$",  # ref excludes pkg/signals the same way
    r"__init__\.py$",  # re-export shims; native/__init__ is gated below
]
# files whose coverage IS load-bearing despite matching an exclusion
FORCE_INCLUDE = [
    r"nexus_tpu/native/__init__\.py$",  # the ctypes binding layer
    # the failover subsystem's package surface: every module under
    # nexus_tpu/ha/ is gated per-file like any other, and the package
    # __init__ re-export shim is gated too so a broken export can't hide
    r"nexus_tpu/ha/__init__\.py$",
    # the round-6 prefix-cache content index: a correctness-critical
    # dedup layer (a bad match serves one request another's K/V) —
    # always gated per-file, whatever future exclusions appear
    r"nexus_tpu/runtime/prefix_cache\.py$",
    # the round-9 admission-ordering policies: scheduling decisions are
    # where a starvation bug hides (ordering never changes tokens, so
    # exactness tests can't see it) — gated per-file
    r"nexus_tpu/runtime/scheduling\.py$",
    # the round-10 host spill tier: demotion/promotion bookkeeping is
    # where a silent host-RAM leak or a stale-payload restore hides
    # (spill/restore never changes tokens either) — gated per-file
    r"nexus_tpu/runtime/host_cache\.py$",
    # the round-7 serve-failover planner: the drain-and-requeue math is
    # where a bug silently loses or duplicates user requests — always
    # gated per-file, whatever future exclusions appear
    r"nexus_tpu/ha/serve_failover\.py$",
    # the round-12 observability package surface: the __init__ re-export
    # shim is gated like ha/'s so a broken export can't hide (the
    # trace/recorder/gauges/exposition modules are gated per-file
    # already — nothing excludes them)
    r"nexus_tpu/obs/__init__\.py$",
    # the round-15 fleet-obs modules: journey stitching is where a
    # silently-dropped leg hides (validators can only flag dumps that
    # exist), the decision log is the audit record itself, and the
    # federation rollups feed dashboards — force-gated per-file,
    # whatever future exclusions appear
    r"nexus_tpu/obs/journey\.py$",
    r"nexus_tpu/obs/fleet_log\.py$",
    r"nexus_tpu/obs/federation\.py$",
    # the round-14 fleet package: routing decides WHICH replica serves
    # a request (a silent bug scatters warm caches, exactness tests
    # can't see it), the autoscaler moves real capacity, and the fleet
    # failover path is where requests get lost — every module gated
    # per-file, the __init__ re-export shim included
    r"nexus_tpu/fleet/.*\.py$",
    # the round-8 enforcement layer itself: a rule or audit whose own
    # coverage rots is a gate that silently stops gating — nexuslint's
    # package __init__ (rule registration) and every rule module, plus
    # the runtime sanitizers, are gated per-file like product code
    r"tools/nexuslint/.*\.py$",
    r"nexus_tpu/testing/sanitizers\.py$",
]


def _excluded(path: str) -> bool:
    for pat in FORCE_INCLUDE:
        if re.search(pat, path):
            return False
    return any(re.search(pat, path) for pat in EXCLUDE)


def check(report_path: str) -> int:
    with open(report_path) as f:
        report = json.load(f)
    failures = []
    packages: dict[str, list[int]] = {}  # pkg -> [covered, statements]
    for path, entry in sorted(report.get("files", {}).items()):
        rel = path.replace(os.sep, "/")
        if _excluded(rel):
            continue
        summary = entry["summary"]
        n = summary.get("num_statements", 0)
        if n == 0:
            continue
        covered = summary.get("covered_lines", 0)
        pct = 100.0 * covered / n
        if pct < FILE_THRESHOLD:
            failures.append(
                f"file {rel}: {pct:.1f}% < {FILE_THRESHOLD:.0f}%"
            )
        pkg = os.path.dirname(rel) or "."
        agg = packages.setdefault(pkg, [0, 0])
        agg[0] += covered
        agg[1] += n
    for pkg, (covered, n) in sorted(packages.items()):
        pct = 100.0 * covered / n
        if pct < PACKAGE_THRESHOLD:
            failures.append(
                f"package {pkg}: {pct:.1f}% < {PACKAGE_THRESHOLD:.0f}%"
            )
    total = report.get("totals", {}).get("percent_covered", 0.0)
    if total < TOTAL_THRESHOLD:
        failures.append(f"total: {total:.1f}% < {TOTAL_THRESHOLD:.0f}%")
    print(
        f"coverage: total {total:.1f}% "
        f"(gates: file {FILE_THRESHOLD:.0f} / package "
        f"{PACKAGE_THRESHOLD:.0f} / total {TOTAL_THRESHOLD:.0f})"
    )
    if failures:
        print(f"{len(failures)} coverage gate violation(s):")
        for f_ in failures:
            print(f"  {f_}")
        return 1
    print("all coverage gates pass")
    return 0


if __name__ == "__main__":
    sys.exit(check(sys.argv[1] if len(sys.argv) > 1 else "coverage.json"))
