"""Trace summaries: jax.profiler device traces AND serve-plane dumps.

Two input kinds, auto-detected:

  * a directory of jax.profiler TensorBoard traces (the original mode):
    top device ops by self time;
  * a ``.json`` file holding a serve-plane observability dump
    (nexus_tpu/obs/): a ``ServeTracer.to_dict()`` span timeline or a
    flight-recorder trip dump — rendered as a human-readable
    per-request timeline / event tail.

Usage::

    python tools/trace_summary.py /tmp/nexus_prof          # profiler
    python tools/trace_summary.py serve_trace.json         # span dump
    python tools/trace_summary.py flight-tmpl-gen0.json    # flight dump
"""
import collections
import glob
import gzip
import json
import os
import sys


def summarize_profiler(root: str) -> None:
    """Top device ops by self time from a jax.profiler trace dir."""
    paths = sorted(glob.glob(f"{root}/**/*.trace.json.gz", recursive=True))
    if not paths:
        sys.exit(f"no trace under {root}")
    path = paths[-1]
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    # device lanes: pid names containing TPU/device
    pid_names = {e["pid"]: e["args"].get("name", "") for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev_pids = {p for p, n in pid_names.items()
                if any(s in n.lower() for s in ("tpu", "device", "xla"))}
    if not dev_pids:  # unknown backend naming (e.g. '/host:CPU'): every lane
        dev_pids = set(pid_names)
    tot = collections.Counter()
    cnt = collections.Counter()
    span = [None, None]
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in dev_pids:
            name = e.get("name", "?")
            dur = e.get("dur", 0)  # us
            tot[name] += dur
            cnt[name] += 1
            ts = e.get("ts", 0)
            if span[0] is None or ts < span[0]:
                span[0] = ts
            te = ts + dur
            if span[1] is None or te > span[1]:
                span[1] = te
    print(f"trace: {path}")
    print(f"pids: { {p: pid_names[p] for p in dev_pids} }")
    if span[0] is not None:
        print(f"device span: {(span[1]-span[0])/1e3:.1f} ms")
    busy = sum(tot.values())
    print(f"total device busy: {busy/1e3:.1f} ms")
    for name, us in tot.most_common(30):
        print(f"{us/1e3:9.2f} ms  x{cnt[name]:4d}  {name[:110]}")


def _span_line(span: dict) -> str:
    """One span → one compact timeline line (schema-ordered fields,
    ``kind`` and ``t`` pulled to the front)."""
    kind = span.get("kind", "?")
    t = span.get("t", 0.0)
    rest = ", ".join(
        f"{k}={v}" for k, v in span.items() if k not in ("kind", "t")
    )
    return f"  {t:9.4f}s  {kind:<14s} {rest}"


def summarize_serve_trace(dump: dict) -> None:
    """Human-readable per-request timeline of a ServeTracer dump."""
    print(f"serve trace: schema v{dump.get('schema_version')}, "
          f"{dump.get('requests')} request(s)")
    for entry in dump.get("spans", []):
        tl = entry.get("timeline", [])
        term = tl[-1] if tl else {}
        status = term.get("status", term.get("kind", "?"))
        print(f"request {entry.get('request')}: {len(tl)} span(s), "
              f"final={status}")
        for span in tl:
            print(_span_line(span))


def summarize_flight_dump(dump: dict) -> None:
    """Event tail of a flight-recorder trip dump."""
    print(f"flight dump: reason={dump.get('reason')!r} "
          f"tripped_t={dump.get('tripped_t')}s "
          f"({len(dump.get('events', []))} event(s) in ring)")
    detail = dump.get("detail") or {}
    if detail:
        print(f"detail: {json.dumps(detail, sort_keys=True)}")
    for ev in dump.get("events", []):
        rest = ", ".join(
            f"{k}={v}" for k, v in ev.items()
            if k not in ("seq", "t", "kind")
        )
        print(f"  #{ev.get('seq', '?'):>5} {ev.get('t', 0.0):9.4f}s  "
              f"{ev.get('kind', '?'):<14s} {rest}")


def main(argv) -> None:
    target = argv[1] if len(argv) > 1 else "/tmp/nexus_prof"
    if os.path.isfile(target) and target.endswith(".json"):
        with open(target) as f:
            dump = json.load(f)
        if "spans" in dump:
            summarize_serve_trace(dump)
        elif "events" in dump:
            summarize_flight_dump(dump)
        else:
            sys.exit(f"{target}: neither a serve trace (spans) nor a "
                     "flight dump (events)")
        return
    summarize_profiler(target)


if __name__ == "__main__":
    try:
        main(sys.argv)
    except BrokenPipeError:  # `| head` closed the pipe — not an error
        sys.exit(0)
