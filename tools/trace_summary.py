"""Summarize a jax.profiler TensorBoard trace: top device ops by self time."""
import glob, gzip, json, sys, collections

root = sys.argv[1] if len(sys.argv) > 1 else "/tmp/nexus_prof"
paths = sorted(glob.glob(f"{root}/**/*.trace.json.gz", recursive=True))
if not paths:
    sys.exit(f"no trace under {root}")
path = paths[-1]
with gzip.open(path, "rt") as f:
    data = json.load(f)
events = data.get("traceEvents", [])
# device lanes: pid names containing TPU/device
pid_names = {e["pid"]: e["args"].get("name", "") for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
dev_pids = {p for p, n in pid_names.items()
            if any(s in n.lower() for s in ("tpu", "device", "xla"))}
if not dev_pids:  # unknown backend naming (e.g. '/host:CPU'): use every lane
    dev_pids = set(pid_names)
tot = collections.Counter()
cnt = collections.Counter()
span = [None, None]
for e in events:
    if e.get("ph") == "X" and e.get("pid") in dev_pids:
        name = e.get("name", "?")
        dur = e.get("dur", 0)  # us
        tot[name] += dur
        cnt[name] += 1
        ts = e.get("ts", 0)
        if span[0] is None or ts < span[0]: span[0] = ts
        te = ts + dur
        if span[1] is None or te > span[1]: span[1] = te
print(f"trace: {path}")
print(f"pids: { {p: pid_names[p] for p in dev_pids} }")
if span[0] is not None:
    print(f"device span: {(span[1]-span[0])/1e3:.1f} ms")
busy = sum(tot.values())
print(f"total device busy: {busy/1e3:.1f} ms")
for name, us in tot.most_common(30):
    print(f"{us/1e3:9.2f} ms  x{cnt[name]:4d}  {name[:110]}")
