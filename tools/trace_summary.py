"""Trace summaries: jax.profiler device traces AND serve-plane dumps.

Input kinds, auto-detected:

  * a directory of jax.profiler TensorBoard traces (the original mode):
    top device ops by self time;
  * a ``.json`` file holding a serve-plane observability dump
    (nexus_tpu/obs/): a ``ServeTracer.to_dict()`` span timeline, a
    flight-recorder trip dump, a CROSS-REPLICA journey dump
    (``JourneyBook.to_dict()`` — one stitched timeline per request,
    legs per replica), a fleet DECISION LOG
    (``FleetDecisionLog.to_dict()`` — routes with their rendezvous/load
    evidence, scale decisions with their samples, drains), or a fleet
    obs trip dump (decision ring + affected journeys) — each rendered
    human-readable.

Usage::

    python tools/trace_summary.py /tmp/nexus_prof          # profiler
    python tools/trace_summary.py serve_trace.json         # span dump
    python tools/trace_summary.py flight-tmpl-gen0.json    # flight dump
    python tools/trace_summary.py journeys.json            # journeys
    python tools/trace_summary.py journeys.json.fleetlog.json  # audit
"""
import collections
import glob
import gzip
import json
import os
import sys


def summarize_profiler(root: str) -> None:
    """Top device ops by self time from a jax.profiler trace dir."""
    paths = sorted(glob.glob(f"{root}/**/*.trace.json.gz", recursive=True))
    if not paths:
        sys.exit(f"no trace under {root}")
    path = paths[-1]
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    # device lanes: pid names containing TPU/device
    pid_names = {e["pid"]: e["args"].get("name", "") for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev_pids = {p for p, n in pid_names.items()
                if any(s in n.lower() for s in ("tpu", "device", "xla"))}
    if not dev_pids:  # unknown backend naming (e.g. '/host:CPU'): every lane
        dev_pids = set(pid_names)
    tot = collections.Counter()
    cnt = collections.Counter()
    span = [None, None]
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in dev_pids:
            name = e.get("name", "?")
            dur = e.get("dur", 0)  # us
            tot[name] += dur
            cnt[name] += 1
            ts = e.get("ts", 0)
            if span[0] is None or ts < span[0]:
                span[0] = ts
            te = ts + dur
            if span[1] is None or te > span[1]:
                span[1] = te
    print(f"trace: {path}")
    print(f"pids: { {p: pid_names[p] for p in dev_pids} }")
    if span[0] is not None:
        print(f"device span: {(span[1]-span[0])/1e3:.1f} ms")
    busy = sum(tot.values())
    print(f"total device busy: {busy/1e3:.1f} ms")
    for name, us in tot.most_common(30):
        print(f"{us/1e3:9.2f} ms  x{cnt[name]:4d}  {name[:110]}")


def _span_line(span: dict) -> str:
    """One span → one compact timeline line (schema-ordered fields,
    ``kind`` and ``t`` pulled to the front)."""
    kind = span.get("kind", "?")
    t = span.get("t", 0.0)
    rest = ", ".join(
        f"{k}={v}" for k, v in span.items() if k not in ("kind", "t")
    )
    return f"  {t:9.4f}s  {kind:<14s} {rest}"


def summarize_serve_trace(dump: dict) -> None:
    """Human-readable per-request timeline of a ServeTracer dump."""
    print(f"serve trace: schema v{dump.get('schema_version')}, "
          f"{dump.get('requests')} request(s)")
    for entry in dump.get("spans", []):
        tl = entry.get("timeline", [])
        term = tl[-1] if tl else {}
        status = term.get("status", term.get("kind", "?"))
        print(f"request {entry.get('request')}: {len(tl)} span(s), "
              f"final={status}")
        for span in tl:
            print(_span_line(span))


def summarize_flight_dump(dump: dict) -> None:
    """Event tail of a flight-recorder trip dump."""
    print(f"flight dump: reason={dump.get('reason')!r} "
          f"tripped_t={dump.get('tripped_t')}s "
          f"({len(dump.get('events', []))} event(s) in ring)")
    detail = dump.get("detail") or {}
    if detail:
        print(f"detail: {json.dumps(detail, sort_keys=True)}")
    for ev in dump.get("events", []):
        rest = ", ".join(
            f"{k}={v}" for k, v in ev.items()
            if k not in ("seq", "t", "kind")
        )
        print(f"  #{ev.get('seq', '?'):>5} {ev.get('t', 0.0):9.4f}s  "
              f"{ev.get('kind', '?'):<14s} {rest}")


def summarize_journeys(dump: dict) -> None:
    """Per-request cross-replica journey timelines (one indented block
    per leg; span ``t`` is engine-local, ``t_start`` fleet-local)."""
    journeys = dump.get("journeys", [])
    stitched = [j for j in journeys if len(j.get("legs", [])) > 1]
    print(f"journeys: schema v{dump.get('schema_version')}, "
          f"{len(journeys)} journey(s), {len(stitched)} cross-replica")
    for rec in journeys:
        legs = rec.get("legs", [])
        path = " -> ".join(leg.get("replica", "?") for leg in legs)
        tl_last = (legs[-1].get("timeline") or [{}])[-1] if legs else {}
        final = tl_last.get("status", tl_last.get("kind", "?"))
        print(f"journey {rec.get('journey')} (request "
              f"{rec.get('request')}): {path}  final={final}")
        for leg in legs:
            print(f"  leg on {leg.get('replica')} "
                  f"(t_start {leg.get('t_start', 0.0):.4f}s):")
            for span in leg.get("timeline", []):
                print("  " + _span_line(span))


def summarize_fleet_log(dump: dict) -> None:
    """The fleet decision audit: one line per event, evidence inline."""
    if dump.get("reason"):
        print(f"fleet obs trip: reason={dump.get('reason')!r} "
              f"tripped_t={dump.get('tripped_t')}s "
              f"detail={json.dumps(dump.get('detail') or {}, sort_keys=True)}")
    print(f"fleet decision log: schema v{dump.get('schema_version')}, "
          f"{len(dump.get('events', []))} event(s) in ring "
          f"({dump.get('events_recorded', '?')} recorded)")
    for ev in dump.get("events", []):
        rest = ", ".join(
            f"{k}={v}" for k, v in ev.items()
            if k not in ("seq", "t", "kind")
        )
        print(f"  #{ev.get('seq', '?'):>5} {ev.get('t', 0.0):9.4f}s  "
              f"{ev.get('kind', '?'):<16s} {rest}")
    if dump.get("reason") and dump.get("journeys", {}).get("journeys"):
        print("--- affected cohort ---")
        summarize_journeys(dump["journeys"])


def main(argv) -> None:
    target = argv[1] if len(argv) > 1 else "/tmp/nexus_prof"
    if os.path.isfile(target) and target.endswith(".json"):
        with open(target) as f:
            dump = json.load(f)
        if "spans" in dump:
            summarize_serve_trace(dump)
        elif "journeys" in dump and "events" in dump:
            summarize_fleet_log(dump)  # fleet obs trip (ring + cohort)
        elif "journeys" in dump:
            summarize_journeys(dump)
        elif "events" in dump and "reason" in dump:
            summarize_flight_dump(dump)
        elif "events" in dump:
            summarize_fleet_log(dump)
        else:
            sys.exit(f"{target}: not a serve trace (spans), journey "
                     "dump (journeys), flight dump, or fleet log "
                     "(events)")
        return
    summarize_profiler(target)


if __name__ == "__main__":
    try:
        main(sys.argv)
    except BrokenPipeError:  # `| head` closed the pipe — not an error
        sys.exit(0)
