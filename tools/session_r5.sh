#!/usr/bin/env bash
# Round-5 on-chip measurement session: one command, strictly sequential
# (exactly ONE process talks to the TPU tunnel at a time), every stage
# with its own deadline, every number landing in a machine-readable
# artifact as it is measured (docs/sweep_r5.jsonl + per-stage JSON).
#
#   bash tools/session_r5.sh [outdir]    # default docs/
#
# Stages:
#   1. python bench.py           — the driver-equivalent full pass
#                                  (train sweep, 1b, decode, serve 8/16,
#                                  trained speculation, int8 long-ctx,
#                                  control-plane p50); populates the
#                                  full-keyed .bench_cache.json
#   2. probe_serve_step.py       — width x rows serving step table
#   3. probe_decode_step.py      — single-stream decode attribution
set -u
cd "$(dirname "$0")/.."
OUT="${1:-docs}"
STAMP="$(date -u +%Y%m%dT%H%M%SZ)"

echo "[session] stage 1: full bench (deadline 1500s)" >&2
python bench.py > "$OUT/bench_session_${STAMP}.json" 2> "$OUT/bench_session_${STAMP}.log"
rc=$?
tail -c 2000 "$OUT/bench_session_${STAMP}.json" >&2 || true
echo "[session] bench rc=$rc" >&2
if [ "$rc" -ne 0 ]; then
  echo "[session] bench failed (tunnel down?) — skipping probes" >&2
  exit "$rc"
fi

echo "[session] stage 2: serve step probe" >&2
NEXUS_PROBE_ROWS="${NEXUS_PROBE_ROWS:-1,4,8,16}" \
timeout 900 python tools/probe_serve_step.py \
  > "$OUT/probe_serve_${STAMP}.json" 2>> "$OUT/bench_session_${STAMP}.log" \
  || echo "[session] serve probe failed (rc=$?)" >&2

echo "[session] stage 3: decode attribution probe" >&2
timeout 900 python tools/probe_decode_step.py \
  > "$OUT/probe_decode_${STAMP}.json" 2>> "$OUT/bench_session_${STAMP}.log" \
  || echo "[session] decode probe failed (rc=$?)" >&2

echo "[session] done; artifacts:" >&2
ls -l "$OUT"/bench_session_${STAMP}.json "$OUT"/probe_*_${STAMP}.json 2>&1 >&2 || true
