"""Observability smoke: a traced mini-serve, validated against schema.

``make obs-smoke`` runs this. It drives the REAL serving engine (cyclic
stub model — CPU, seconds) through the full obs surface and gates every
artifact on its validator:

  1. a traced serve run → ``ServeTracer`` dump validates
     (``validate_trace``), every request's timeline is
     enqueued → ... → terminal, and token outputs are EXACT (tracing
     must never perturb serving);
  2. a cancelled serve run → the flight recorder trips on drain, the
     dump validates (``validate_flight_dump``), and its drain events
     match the engine's drain snapshot request for request;
  3. live gauges land in the in-process registry and the Prometheus /
     JSON expositions render them.

Writes the two dumps under ``--out`` (default /tmp/nexus_obs_smoke) so
``python tools/trace_summary.py <dump>.json`` has something real to
render. Exit 0 = clean, 1 = violation (details printed).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ONE copy of the cyclic serve stub (next = (token + 1) % v, honoring
# the chunked-prefill n_valid contract) lives in tools/ — reuse the
# outage bench's, so an engine cache-contract change is fixed once
from tools.bench_serve_outage import _cyclic_model, _expected  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="/tmp/nexus_obs_smoke")
    args = ap.parse_args(argv)

    from nexus_tpu.obs import (
        ServeTracer,
        registry_snapshot,
        render_prometheus,
        validate_flight_dump,
        validate_trace,
        write_dump,
    )
    from nexus_tpu.runtime.serving import ServeRequest, ServingEngine
    from nexus_tpu.utils.signals import CancelToken
    from nexus_tpu.utils.telemetry import StatsdClient, with_statsd

    problems: list = []
    v = 13
    cfg, fwd = _cyclic_model(v)
    # a fresh process-default registry so the gauge assertions below
    # see exactly this smoke's series
    client: StatsdClient = with_statsd("obs-smoke")

    # ---- 1. traced serve run: schema + exactness ----
    tracer = ServeTracer()
    eng = ServingEngine(
        fwd, {}, cfg, batch_size=2, max_len=128, chunk=4,
        kv_block_size=8, tracer=tracer, gauge_tags=["engine:smoke-0"],
    )
    # shared preamble so the radix tree attributes hits in the spans
    reqs = [
        ServeRequest(prompt=[0, 1, 2, 3, 4, 5, 6, 7, (i % 5) + 1],
                     max_new_tokens=12)
        for i in range(6)
    ]
    results, metrics = eng.serve(reqs)
    for i, (req, res) in enumerate(zip(reqs, results)):
        if res.tokens != _expected(req, v):
            problems.append(f"request {i}: traced output diverged")
    dump = tracer.to_dict()
    problems += [f"trace: {p}" for p in validate_trace(dump)]
    for entry in dump["spans"]:
        kinds = [s["kind"] for s in entry["timeline"]]
        for needed in ("enqueued", "admitted", "first_token", "terminal"):
            if needed not in kinds:
                problems.append(
                    f"request {entry['request']}: no {needed!r} span "
                    f"(got {kinds})"
                )
    if metrics.get("live_gauge_publishes", 0) < 1:
        problems.append("engine published no live gauges")
    trace_path = write_dump(dump, os.path.join(args.out, "serve_trace.json"))

    # ---- 2. kill-mid-serve: the flight recorder trips on drain ----
    eng2 = ServingEngine(
        fwd, {}, cfg, batch_size=1, max_len=128, chunk=4, kv_block_size=8,
    )
    cancel = CancelToken()
    beats = [0]

    def hb(_committed):
        beats[0] += 1
        if beats[0] >= 2:  # mid-decode, after real waves committed
            cancel.cancel(hard=True)

    reqs2 = [ServeRequest(prompt=[0, i + 1], max_new_tokens=40)
             for i in range(3)]
    _res2, m2 = eng2.serve(reqs2, cancel=cancel, heartbeat=hb)
    if not m2.get("interrupted"):
        problems.append("cancel never drained the engine")
    fdump = eng2.last_flight_dump
    if fdump is None:
        problems.append("drain did not trip the flight recorder")
    else:
        problems += [f"flight: {p}" for p in validate_flight_dump(fdump)]
        if fdump["reason"] != "drain":
            problems.append(f"trip reason {fdump['reason']!r} != 'drain'")
        drained_ids = sorted(
            d.request_idx for d in (eng2.last_drain or [])
        )
        dump_ids = sorted(fdump["detail"].get("drained", []))
        if drained_ids != dump_ids:
            problems.append(
                f"dump drained set {dump_ids} != engine drain snapshot "
                f"{drained_ids}"
            )
        tail_ids = sorted(
            ev["request"] for ev in fdump["events"]
            if ev["kind"] == "drain_request"
        )
        if tail_ids != drained_ids:
            problems.append(
                f"dump tail drain events {tail_ids} != drain snapshot "
                f"{drained_ids}"
            )
        write_dump(fdump, os.path.join(args.out, "flight_drain.json"))

    # ---- 3. exposition over the live registry ----
    text = render_prometheus(client)
    if "nexus_tpu" in text:
        problems.append("exposition leaked another app's registry")
    for metric in ("obs_smoke.serve_queue_depth",
                   "obs_smoke.serve_committed_tokens"):
        prom = metric.replace(".", "_").replace("-", "_")
        if prom not in text:
            problems.append(f"{metric} missing from Prometheus text")
    snap = registry_snapshot(client)
    if not any(s["tags"] == ["engine:smoke-0"] for s in snap["series"]):
        problems.append("gauge_tags never reached the registry series")

    if problems:
        print("OBS SMOKE FAILED:")
        for p in problems[:20]:
            print(f"  - {p}")
        return 1
    print(
        f"obs smoke clean: {metrics['requests']} traced requests, "
        f"{sum(len(e['timeline']) for e in dump['spans'])} spans, "
        f"{metrics['flight_recorder_events']} flight events, "
        f"{metrics['live_gauge_publishes']} gauge publishes; dumps in "
        f"{args.out} (render: python tools/trace_summary.py "
        f"{trace_path})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
