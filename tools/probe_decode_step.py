"""On-chip single-stream decode attribution probe (VERDICT r4 item 6).

Single-stream decode measured 191.7 tok/s at 349M bf16 on v5e — ~16% of
the HBM roofline — and no artifact says where the other ~84% goes. This
tool produces the attribution table:

  1. ``scan_ms``        — per-step cost inside one jitted lax.scan of K
                          decode steps (the bench's own regime: dispatch
                          amortized; the number 1/tok_s implies)
  2. ``single_ms``      — one jitted decode step, host-fetch closed
                          (adds per-dispatch + tunnel RTT)
  3. ``stream_ms``      — a jitted "touch every param once" reduction
                          (the achievable weight-stream floor for this
                          layout; pure HBM read, near-zero FLOPs)
  4. ``lm_head_ms``     — the (1,d)x(d,V) logits matmul alone
  5. ``sample_ms``      — argmax/sampling on (1, V) logits alone

plus the byte model (param bytes, KV bytes at the probed context) and
derived ratios: scan_ms/stream_ms is the decode step's distance from its
own weight-stream floor with dispatch removed; single_ms - scan_ms is
the per-dispatch overhead the serving engine's chunked host loop pays
once per CHUNK (not per token).

    python tools/probe_decode_step.py              # attached TPU
    NEXUS_PROBE_PRESET=400m NEXUS_PROBE_CTX=576 NEXUS_PROBE_SCAN=64 ...

Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_best(fn, reps=5):
    """min-of-reps wall time of fn() with the window closed by the caller
    inside fn (host fetch)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.monotonic()
        fn()
        best = min(best, time.monotonic() - t0)
    return best


def main() -> int:
    from nexus_tpu.utils.hw import (
        device_kind, honor_env_platforms, is_tpu, sync_host,
    )

    honor_env_platforms()
    from nexus_tpu.utils.hw import enable_persistent_compilation_cache

    # tunnel-compile cache shared with bench.py (helper no-ops unless the
    # resolved backend is a real TPU or NEXUS_XLA_CACHE_DIR opts in)
    enable_persistent_compilation_cache(repo_default=True)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nexus_tpu.models import llama
    from nexus_tpu.models.decoding import init_kv_cache
    from nexus_tpu.train.metrics import detect_generation

    print(f"[probe] backend: {device_kind()}", file=sys.stderr, flush=True)
    preset = os.environ.get("NEXUS_PROBE_PRESET") or (
        "400m" if is_tpu() else "tiny"
    )
    ctx = int(os.environ.get("NEXUS_PROBE_CTX") or 576)
    scan_k = int(os.environ.get("NEXUS_PROBE_SCAN") or 64)
    overrides = {} if is_tpu() else {"dtype": "float32"}
    cfg = llama.config(preset, **overrides)
    params = llama.init(jax.random.PRNGKey(0), cfg)

    dt_bytes = int(np.dtype(cfg.dtype).itemsize)
    n_params = cfg.param_count()
    param_gb = n_params * dt_bytes / 1e9
    kv_gb = (
        cfg.n_layers * ctx * cfg.n_kv_heads * cfg.head_dim * 2 * dt_bytes
        / 1e9
    )
    out = {
        "preset": preset,
        "ctx": ctx,
        "param_count": n_params,
        "param_gb": round(param_gb, 4),
        "kv_gb_at_ctx": round(kv_gb, 4),
        "device": device_kind(),
    }

    def fresh_cache():
        c = init_kv_cache(
            cfg.n_layers, cfg.n_kv_heads, cfg.head_dim, cfg.dtype, 1, ctx,
        )
        # SCALAR cache length (ADVICE r5): the batch-1 decode leg this
        # probe attributes runs the scalar-length cache, whose write is a
        # contiguous dynamic_update_slice — a (1,)-vector length would
        # compile the per-row scatter program instead and attribute the
        # wrong step cost
        c["length"] = jnp.asarray(ctx // 2, jnp.int32)
        return c

    tok = jnp.zeros((1, 1), jnp.int32)

    # 1. per-step cost with dispatch amortized (one jit, K chained steps)
    @jax.jit
    def scan_steps(params, cache, tok):
        def step(carry, _):
            tok, cache = carry
            logits, cache = llama.forward_decode(params, cfg, tok, cache)
            nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None].astype(
                jnp.int32
            )
            return (nxt, cache), ()

        (tok, cache), _ = jax.lax.scan(
            step, (tok, cache), None, length=scan_k
        )
        return tok

    # one cache hoisted outside the timing window: scan_steps neither
    # donates nor mutates its argument, and allocating it per rep would
    # put cache-creation dispatches inside the very measurement that
    # exists to exclude per-dispatch overhead
    cache0 = fresh_cache()
    sync_host(scan_steps(params, cache0, tok))  # compile + warm
    scan_s = _time_best(
        lambda: sync_host(scan_steps(params, cache0, tok))
    )
    out["scan_ms"] = round(scan_s / scan_k * 1e3, 3)
    out["scan_tok_s"] = round(scan_k / scan_s, 1)

    # 2. one dispatched step (adds per-dispatch/tunnel overhead)
    @jax.jit
    def one_step(params, cache, tok):
        logits, cache = llama.forward_decode(params, cfg, tok, cache)
        return jnp.argmax(logits[:, -1], axis=-1)

    cache1 = fresh_cache()
    sync_host(one_step(params, cache1, tok))
    single_s = _time_best(lambda: sync_host(one_step(params, cache1, tok)))
    out["single_ms"] = round(single_s * 1e3, 3)

    # 3. weight-stream floor: touch every param byte once, ~no FLOPs
    @jax.jit
    def stream(params):
        return sum(
            jnp.sum(x.astype(jnp.float32))
            for x in jax.tree_util.tree_leaves(params)
        )

    sync_host(stream(params))
    stream_s = _time_best(lambda: sync_host(stream(params)))
    out["stream_ms"] = round(stream_s * 1e3, 3)
    out["stream_gb_s"] = round(param_gb / stream_s, 1)

    # 4. lm head alone (the single largest weight read)
    w_lm = params["lm_head"] if "lm_head" in params else None
    if w_lm is not None:
        x = jnp.zeros((1, cfg.d_model), cfg.dtype)

        @jax.jit
        def lm_head(x, w):
            return x @ w

        sync_host(lm_head(x, w_lm))
        out["lm_head_ms"] = round(
            _time_best(lambda: sync_host(lm_head(x, w_lm))) * 1e3, 3
        )

    # 5. sampling alone
    logits = jnp.zeros((1, cfg.vocab_size), jnp.float32)

    @jax.jit
    def pick(logits):
        return jnp.argmax(logits, axis=-1)

    sync_host(pick(logits))
    out["sample_ms"] = round(
        _time_best(lambda: sync_host(pick(logits))) * 1e3, 3
    )

    # derived attribution — bandwidth keyed off the ONE device-kind
    # alias matcher the rest of the repo uses (train/metrics.py)
    hbm_by_gen = {"v4": 1228.0, "v5e": 819.0, "v5p": 2765.0,
                  "v6e": 1640.0}
    bw = hbm_by_gen.get(detect_generation(device_kind()) or "")
    if bw:
        out["roofline_ms"] = round((param_gb + kv_gb) / bw * 1e3, 3)
        out["scan_vs_roofline"] = round(
            out["roofline_ms"] / out["scan_ms"], 3
        ) if out["scan_ms"] else None
    out["dispatch_overhead_ms"] = round(
        out["single_ms"] - out["scan_ms"], 3
    )
    out["scan_vs_stream"] = (
        round(out["stream_ms"] / out["scan_ms"], 3) if out["scan_ms"] else None
    )
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
