"""Race smoke: hammer one StatsdClient registry from N emitter threads.

The in-process metrics registry stopped being a write-at-end,
read-at-end structure in PR 12: the serve engine's wave loop publishes
live gauges while controller/supervisor threads emit their own series
and the exposition renderer reads snapshots concurrently (the
``/metrics``-scrape shape). This smoke exercises exactly that mix —
the telemetry twin of ``tools/race_smoke_store.py``.

Invariants checked:

  * no exception escapes any emitter or reader thread;
  * PER-SERIES MONOTONICITY through snapshots: each emitter publishes a
    strictly increasing counter into its own (name, tags) series, so a
    snapshot that ever shows a series value going backwards caught a
    torn/lost write;
  * renders are internally consistent: every sample line in the
    Prometheus text parses, and after quiesce the final snapshot holds
    every emitter's LAST published value exactly;
  * the history ring stays bounded at ``StatsdClient.HISTORY_CAP``.

Exit code 0 = clean, 1 = violation (details printed).

Usage: python tools/race_smoke_telemetry.py [--threads 8] [--seconds 2]
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nexus_tpu.obs.exposition import (  # noqa: E402
    registry_snapshot,
    render_prometheus,
)
from nexus_tpu.utils.telemetry import StatsdClient  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=2.0)
    args = ap.parse_args(argv)

    client = StatsdClient("race-smoke")
    stop = threading.Event()
    violations: list = []
    last_published = [0] * args.threads

    def emitter(i: int) -> None:
        n = 0
        try:
            while not stop.is_set():
                n += 1
                client.gauge("serve_counter", n, tags=[f"emitter:{i}"])
                # a shared untagged series too — last-writer-wins race
                client.gauge("serve_shared", n)
                last_published[i] = n
        except Exception as e:  # noqa: BLE001 — the smoke's whole point
            violations.append(f"emitter {i}: {type(e).__name__}: {e}")

    def reader() -> None:
        seen: dict = {}
        try:
            while not stop.is_set():
                snap = registry_snapshot(client)
                for s in snap["series"]:
                    key = (s["name"], tuple(s["tags"]))
                    prev = seen.get(key, 0)
                    if s["value"] < prev and s["name"].endswith("counter"):
                        violations.append(
                            f"series {key} went backwards: "
                            f"{prev} -> {s['value']}"
                        )
                        return
                    seen[key] = max(prev, s["value"])
                text = render_prometheus(client)
                for line in text.splitlines():
                    if line.startswith("#"):
                        continue
                    # name{labels} value — the value must parse
                    try:
                        float(line.rsplit(" ", 1)[1])
                    except (IndexError, ValueError):
                        violations.append(f"unparseable sample: {line!r}")
                        return
        except Exception as e:  # noqa: BLE001
            violations.append(f"reader: {type(e).__name__}: {e}")

    threads = [
        threading.Thread(target=emitter, args=(i,), daemon=True)
        for i in range(args.threads)
    ] + [threading.Thread(target=reader, daemon=True)]
    for t in threads:
        t.start()
    time.sleep(args.seconds)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)

    # quiesced: the final snapshot must hold every emitter's last value
    snap = registry_snapshot(client)
    series = {
        (s["name"], tuple(s["tags"])): s["value"] for s in snap["series"]
    }
    for i in range(args.threads):
        key = ("race-smoke.serve_counter", (f"emitter:{i}",))
        got = series.get(key)
        if got != last_published[i]:
            violations.append(
                f"final snapshot lost emitter {i}'s last write: "
                f"{got} != {last_published[i]}"
            )
    if snap["history_len"] > StatsdClient.HISTORY_CAP:
        violations.append(
            f"history unbounded: {snap['history_len']} > "
            f"{StatsdClient.HISTORY_CAP}"
        )

    if violations:
        print("TELEMETRY RACE SMOKE FAILED:")
        for v in violations[:20]:
            print(f"  - {v}")
        return 1
    total = sum(last_published)
    print(
        f"telemetry race smoke clean: {args.threads} emitters x "
        f"{args.seconds}s, {total} gauge writes, "
        f"{len(series)} series surviving, history_len="
        f"{snap['history_len']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
