"""Hermetic failover bench: time-to-recover p50 (CPU-only, no TPU).

Measures the whole failover pipeline through the REAL control plane —
heartbeat leases → flap-suppressed failure detector → planner (restore-step
annotation, dead-Job reap, sticky-home eviction) → placement re-run →
re-materialized Job on a healthy shard — with simulated workers standing in
for TPU pods (they renew leases, write real npz checkpoints, and honor the
``NEXUS_RESTORE_STEP`` env the materializer stamps, so the annotation →
env → resume plumbing is exercised end to end; the *training* side of
resume is proven by tests/test_failover.py with a real mlp run).

Per trial: kill the worker on its home shard (hard — no final checkpoint,
no done-marker), then clock until a worker is running *on a different
shard* with the correct restore step.

  time_to_recover = detection (missed deadlines → confirmation)
                  + re-place   (planner + reconcile + Job create)
                  + resume     (worker start at the restored step)

Prints ONE JSON line: {"metric": "failover_time_to_recover_p50_s", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class SimWorker(threading.Thread):
    """A TPU-pod stand-in bound to one materialized Job: marks it Running,
    resumes from NEXUS_RESTORE_STEP (or the latest durable checkpoint),
    then steps at a fixed rate — renewing its heartbeat lease through the
    shard store and writing an npz checkpoint every ``ckpt_interval``
    steps. ``kill()`` stops everything silently (no final checkpoint, no
    done-marker): the failure the detector must confirm."""

    def __init__(self, store, job, ckpt_dir: str, ttl: float,
                 steps_per_sec: float, ckpt_interval: int):
        super().__init__(daemon=True, name=f"sim-worker-{store.name}")
        from nexus_tpu.ha.lease import LeaseRenewer
        from nexus_tpu.runtime.materializer import LABEL_TEMPLATE
        from nexus_tpu.train.checkpoint import NpzCheckpointer, latest_step

        self.store = store
        self.job = job
        self.template = (job.metadata.labels or {}).get(LABEL_TEMPLATE, "")
        self.namespace = job.metadata.namespace
        self.ckpt = NpzCheckpointer(ckpt_dir, keep=3)
        env = {
            e.get("name"): e.get("value", "")
            for e in job.spec["template"]["spec"]["containers"][0]["env"]
        }
        if env.get("NEXUS_RESTORE_STEP", ""):
            self.resume_step = int(env["NEXUS_RESTORE_STEP"])
        else:
            self.resume_step = latest_step(ckpt_dir) or 0
        self.step = self.resume_step
        self.steps_per_sec = steps_per_sec
        self.ckpt_interval = ckpt_interval
        self.renewer = LeaseRenewer(
            store, self.namespace, self.template,
            holder=f"sim-{store.name}", ttl_seconds=ttl,
        )
        self._killed = threading.Event()
        self.running = threading.Event()

    def kill(self) -> None:
        self._killed.set()

    def run(self) -> None:
        import numpy as np

        self._mark_running()
        self.running.set()
        tick = 1.0 / self.steps_per_sec
        state = {"params": {"w": np.zeros(8, dtype=np.float32)},
                 "opt": np.zeros(8, dtype=np.float32)}
        while not self._killed.wait(tick):
            self.step += 1
            self.renewer.renew(self.step)
            if self.step % self.ckpt_interval == 0:
                self.ckpt.save(state, step=self.step)

    def _mark_running(self) -> None:
        from datetime import datetime, timezone

        from nexus_tpu.api.workload import Job

        try:
            job = self.store.get(Job.KIND, self.namespace,
                                 self.job.metadata.name)
            job.status.active = 1
            job.status.ready = 1
            job.status.start_time = datetime.now(timezone.utc).isoformat()
            self.store.update_status(job)
        except Exception:  # noqa: BLE001 — raced the reconciler; harmless
            pass


def _make_template(name: str, ns: str, ckpt_dir: str):
    from nexus_tpu.api.runtime_spec import (
        CheckpointSpec,
        JaxXlaRuntime,
        ModelRef,
        ParallelismSpec,
        TpuSliceSpec,
        TrainSpec,
    )
    from nexus_tpu.api.template import (
        Container,
        NexusAlgorithmSpec,
        NexusAlgorithmTemplate,
        RuntimeEnvironment,
        WorkgroupRef,
    )
    from nexus_tpu.api.types import ObjectMeta

    tmpl = NexusAlgorithmTemplate(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=NexusAlgorithmSpec(
            container=Container(
                image="algo", registry="ghcr.io/bench", version_tag="v1",
            ),
            workgroup_ref=WorkgroupRef(name="wg-failover"),
            runtime_environment=RuntimeEnvironment(),
        ),
    )
    tmpl.spec.runtime = JaxXlaRuntime(
        mode="train",
        model=ModelRef(family="mlp", preset="tiny"),
        tpu=TpuSliceSpec(accelerator="v5e", topology="1x1", slice_count=1),
        parallelism=ParallelismSpec(),
        train=TrainSpec(batch_size=8, steps=10_000),
        checkpoint=CheckpointSpec(
            enabled=True, directory=ckpt_dir, format="npz",
        ),
    )
    return tmpl


def run_bench(n_trials: int = 5, ttl: float = 0.5, probe: float = 0.1,
              steps_per_sec: float = 200.0, ckpt_interval: int = 50,
              timeout_s: float = 30.0) -> dict:
    import tempfile

    from nexus_tpu.api.workgroup import (
        NexusAlgorithmWorkgroup,
        NexusAlgorithmWorkgroupSpec,
    )
    from nexus_tpu.api.workload import Job
    from nexus_tpu.api.types import ObjectMeta
    from nexus_tpu.cluster.store import ClusterStore
    from nexus_tpu.controller.controller import Controller
    from nexus_tpu.ha.failover import FailoverConfig
    from nexus_tpu.shards.shard import Shard
    from nexus_tpu.utils.telemetry import (
        METRIC_FAILOVER_DETECTION_SECONDS,
        StatsdClient,
    )

    ns = "nexus-failover-bench"
    ckpt_dir = tempfile.mkdtemp(prefix="nexus_failover_bench_")
    ctrl_store = ClusterStore("controller")
    shard_stores = [ClusterStore("shard0"), ClusterStore("shard1")]
    shards = [Shard("bench", s.name, s) for s in shard_stores]
    statsd = StatsdClient("bench")
    controller = Controller(
        ctrl_store, shards, statsd=statsd, resync_period=5.0,
        failover=FailoverConfig(
            heartbeat_ttl=ttl, probe_interval=probe,
            suspect_misses=2, api_failure_threshold=3,
        ),
    )

    workers: dict = {}  # shard name -> SimWorker
    workers_lock = threading.Lock()

    def watch_jobs(store):
        def on_event(ev):
            if ev.type != "ADDED":
                return
            w = SimWorker(store, ev.obj, ckpt_dir, ttl,
                          steps_per_sec, ckpt_interval)
            with workers_lock:
                workers[store.name] = w
            w.start()

        store.subscribe(Job.KIND, on_event)

    for s in shard_stores:
        watch_jobs(s)

    def wait_for_worker(exclude: str = "", timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with workers_lock:
                for name, w in workers.items():
                    if name != exclude and w.running.is_set() and not w._killed.is_set():
                        return w
            time.sleep(0.01)
        return None

    result: dict = {"metric": "failover_time_to_recover_p50_s"}
    recover_s, steps_lost, failed = [], [], 0
    try:
        controller.run(workers=2)
        ctrl_store.create(NexusAlgorithmWorkgroup(
            metadata=ObjectMeta(name="wg-failover", namespace=ns),
            spec=NexusAlgorithmWorkgroupSpec(scheduling="any"),
        ))
        ctrl_store.create(_make_template("failover-bench", ns, ckpt_dir))

        current = wait_for_worker(timeout=timeout_s)
        if current is None:
            return {**result, "error": "initial placement never ran a worker"}
        for _ in range(n_trials):
            # let the worker make progress past at least one durable save
            target = current.step + ckpt_interval + ckpt_interval // 2
            deadline = time.monotonic() + timeout_s
            while current.step < target and time.monotonic() < deadline:
                time.sleep(0.01)
            kill_step = current.step
            died_on = current.store.name
            t_kill = time.monotonic()
            current.kill()
            nxt = wait_for_worker(exclude=died_on, timeout=timeout_s)
            if nxt is None:
                failed += 1
                break
            recover_s.append(time.monotonic() - t_kill)
            steps_lost.append(max(kill_step - nxt.resume_step, 0))
            current = nxt
        if not recover_s:
            return {**result, "error": "no trial recovered", "failed": failed}
        import math

        recover_s.sort()
        p = lambda q: recover_s[max(0, math.ceil(q * len(recover_s)) - 1)]  # noqa: E731
        with statsd._lock:
            detections = sorted(
                v for (name, v, _t) in statsd.history
                if name == f"bench.{METRIC_FAILOVER_DETECTION_SECONDS}"
            )
        result.update({
            "value": round(p(0.50), 4),
            "unit": "seconds",
            "p90_s": round(p(0.90), 4),
            "max_s": round(recover_s[-1], 4),
            "n_trials": len(recover_s),
            "failed_trials": failed,
            "detection_p50_s": round(
                detections[len(detections) // 2], 4
            ) if detections else None,
            "replace_resume_p50_s": round(
                p(0.50) - detections[len(detections) // 2], 4
            ) if detections else None,
            "failover_steps_lost_mean": round(
                sum(steps_lost) / len(steps_lost), 2
            ),
            "heartbeat_ttl_s": ttl,
            "probe_interval_s": probe,
            "ckpt_interval_steps": ckpt_interval,
            "steps_per_sec": steps_per_sec,
            "failovers_total": controller.failover_manager.failovers_total,
        })
        return result
    finally:
        with workers_lock:
            for w in workers.values():
                w.kill()
        try:
            controller.stop()
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--ttl", type=float, default=0.5,
                    help="heartbeat TTL seconds (bench-scaled; prod 15)")
    ap.add_argument("--probe", type=float, default=0.1,
                    help="detector probe interval seconds (prod 5)")
    ap.add_argument("--steps-per-sec", type=float, default=200.0)
    ap.add_argument("--ckpt-interval", type=int, default=50)
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)
    result = run_bench(args.trials, args.ttl, args.probe,
                       args.steps_per_sec, args.ckpt_interval, args.timeout)
    print(json.dumps(result), flush=True)
    return 0 if "value" in result else 1


if __name__ == "__main__":
    sys.exit(main())
