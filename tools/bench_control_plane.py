"""Hermetic control-plane latency bench: template-to-running p50.

BASELINE config #3 tracks ``template_to_running`` p50 for template-driven
inference; the controller emits the gauges
(``controller/controller.py::_observe_template_to_running``) but no artifact
ever published a number (VERDICT r4 item 7). This tool measures it through
the REAL controller path, CPU-only, no TPU:

  two in-process API servers (controller + shard) over real HTTP sockets ->
  production ``KubeClusterStore`` clients -> the real ``Controller`` with
  its workload plane materializing Jobs on the shard -> a kubelet stand-in
  marking those Jobs Running (stamping ``status.startTime``) -> the
  controller's own ``template_to_running_seconds`` gauge per template.

Equivalent discipline in the reference: its e2e suite asserts the
create->visible-on-shard latency envelope against two kind clusters
(/root/reference/controller_test.go:1304-1328); here the envelope is
measured and published rather than asserted.

Prints ONE JSON line: {"metric": "template_to_running_p50_s", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_runtime_template(name: str, ns: str):
    """A template carrying a jax_xla runtime block so the workload plane
    engages (Jobs materialized on the shard) — mirrors the shape the
    workload e2e tier uses (tests/test_workload.py)."""
    from nexus_tpu.api.runtime_spec import (
        JaxXlaRuntime,
        ModelRef,
        ParallelismSpec,
        TpuSliceSpec,
        TrainSpec,
    )
    from nexus_tpu.api.template import (
        ComputeResources,
        Container,
        NexusAlgorithmSpec,
        NexusAlgorithmTemplate,
        RuntimeEnvironment,
        WorkgroupRef,
    )
    from nexus_tpu.api.types import ObjectMeta

    tmpl = NexusAlgorithmTemplate(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=NexusAlgorithmSpec(
            container=Container(
                image="algo", registry="ghcr.io/bench",
                version_tag="v1.0.0", service_account_name="nexus-sa",
            ),
            compute_resources=ComputeResources(
                cpu_limit="4", memory_limit="8Gi"
            ),
            workgroup_ref=WorkgroupRef(
                name="wg-bench", group="science.sneaksanddata.com",
                kind="NexusAlgorithmWorkgroup",
            ),
            command="python",
            args=["run.py"],
            runtime_environment=RuntimeEnvironment(),
        ),
    )
    tmpl.spec.runtime = JaxXlaRuntime(
        mode="train",
        model=ModelRef(family="llama", preset="tiny"),
        tpu=TpuSliceSpec(accelerator="v5e", topology="2x2", slice_count=1),
        parallelism=ParallelismSpec(data=2, tensor=2),
        train=TrainSpec(batch_size=8, seq_len=32, steps=2),
    )
    return tmpl


def run_bench(n_templates: int = 24, workers: int = 2,
              timeout_s: float = 120.0, stagger_s: float = 0.0,
              n_shards: int = 1, shard_sync_workers: int = 0,
              write_skip: bool = True, shard_latency_s: float = 0.0) -> dict:
    from nexus_tpu.api.template import NexusAlgorithmTemplate
    from nexus_tpu.api.workload import Job
    from nexus_tpu.cluster.kube import KubeClusterStore
    from nexus_tpu.controller.controller import Controller
    from nexus_tpu.shards.shard import Shard
    from nexus_tpu.testing.fakekube import FakeKubeApiServer
    from nexus_tpu.utils.telemetry import (
        METRIC_TEMPLATE_TO_RUNNING,
        METRIC_TEMPLATE_TO_RUNNING_P50,
        StatsdClient,
    )

    ns = "nexus-bench"
    ctrl_srv = FakeKubeApiServer(name="controller").start()
    # shard servers optionally simulate a cross-cluster RTT per request —
    # the thing the in-process servers otherwise hide (a remote shard's API
    # server is a network round trip away, which is exactly what the
    # parallel fan-out overlaps)
    shard_srvs = [
        FakeKubeApiServer(name=f"shard{i}", latency_s=shard_latency_s).start()
        for i in range(n_shards)
    ]
    import tempfile

    tmp = tempfile.mkdtemp(prefix="nexus_cp_bench_")
    ctrl_cfg = ctrl_srv.write_kubeconfig(f"{tmp}/controller.kubeconfig")
    ctrl_store = KubeClusterStore("controller", ctrl_cfg, namespace=ns)
    shard_stores = []
    for i, srv in enumerate(shard_srvs):
        cfg = srv.write_kubeconfig(f"{tmp}/shard{i}.kubeconfig")
        shard_stores.append(KubeClusterStore(f"shard{i}", cfg, namespace=ns))
    statsd = StatsdClient("bench")
    controller = Controller(
        ctrl_store,
        [Shard("bench", f"shard{i}", s) for i, s in enumerate(shard_stores)],
        statsd=statsd, resync_period=5.0,
        # 1 = the strictly sequential reference fan-out (baseline mode);
        # 0 = auto-sized parallel fan-out (the product default)
        shard_sync_workers=shard_sync_workers,
        write_skip_cache=write_skip,
    )

    stop = threading.Event()
    pending_jobs: list = []
    pending_cv = threading.Condition()

    def watch_jobs(srv):
        """Event-driven kubelet stand-in feed: a Job appearing on the shard
        API server queues it for the marker thread (polling with full LISTs
        burned ~30% of a core at burst scale and skewed the measurement)."""

        def on_event(ev):
            if ev.type in ("ADDED", "MODIFIED"):
                with pending_cv:
                    pending_jobs.append((srv, ev.obj))
                    pending_cv.notify()

        srv.store.subscribe(Job.KIND, on_event)

    def kubelet_standin():
        """Mark every materialized Job Running (active=1, startTime
        stamped) the moment it appears on the shard API server — the
        role a kubelet plays in the reference's kind-cluster e2e."""
        from datetime import datetime, timezone

        while not stop.is_set():
            with pending_cv:
                if not pending_jobs:
                    pending_cv.wait(timeout=0.25)
                batch, pending_jobs[:] = list(pending_jobs), []
            for srv, job in batch:
                if job.status.active or job.status.succeeded:
                    continue
                job.status.active = 1
                job.status.ready = 1
                job.status.start_time = datetime.now(
                    timezone.utc
                ).isoformat()
                try:
                    srv.store.update_status(job)
                except Exception:  # noqa: BLE001 — raced an update
                    pass

    for srv in shard_srvs:
        watch_jobs(srv)
    kubelet = threading.Thread(target=kubelet_standin, daemon=True)
    t0 = time.monotonic()
    result: dict = {"metric": "template_to_running_p50_s"}
    try:
        controller.run(workers=workers)
        kubelet.start()
        for i in range(n_templates):
            # burst (stagger 0) measures a thundering-herd create; a
            # stagger spaces arrivals so later samples are steady-state
            if stagger_s and i:
                time.sleep(stagger_s)
            ctrl_store.create(_make_runtime_template(f"algo-{i:03d}", ns))
        metric_name = f"bench.{METRIC_TEMPLATE_TO_RUNNING}"
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with statsd._lock:
                samples = [
                    v for (name, v, _tags) in statsd.history
                    if name == metric_name
                ]
            if len(samples) >= n_templates:
                break
            time.sleep(0.05)
        wall_s = time.monotonic() - t0
        samples.sort()
        if not samples:
            return {**result, "error": "no template_to_running samples",
                    "wall_s": round(wall_s, 3)}
        if len(samples) < n_templates:
            # deadline hit with stragglers outstanding: the surviving
            # subset is the FASTEST completions, so its p50 is biased low
            # — flag it so consumers don't publish it as the real p50
            result["partial"] = True
        import math

        # nearest-rank percentile: ceil(q*n)-1 (int(q*n) is one rank high
        # — at n=16 it would report the 9th value, ~p56, as the median)
        p = lambda q: samples[max(0,  # noqa: E731
                                  math.ceil(q * len(samples)) - 1)]
        coalesced = getattr(controller.work_queue, "coalesced_total", None)
        result.update({
            "value": round(p(0.50), 4),
            "unit": "seconds",
            "p90_s": round(p(0.90), 4),
            "max_s": round(samples[-1], 4),
            "n_templates": n_templates,
            "n_samples": len(samples),
            "workers": workers,
            "n_shards": n_shards,
            "shard_sync_workers": controller.shard_executor.max_workers,
            "stagger_s": stagger_s,
            "shard_latency_s": shard_latency_s,
            "wall_s": round(wall_s, 3),
            # burst-visibility counters from the reconcile hot path
            "coalesced_total": coalesced() if coalesced is not None else None,
            "write_skip": controller.write_skip_cache.stats(),
            # the controller's own rolling-p50 gauge agrees by construction
            "controller_p50_gauge": statsd.gauges.get(
                f"bench.{METRIC_TEMPLATE_TO_RUNNING_P50}"
            ),
        })
        return result
    finally:
        stop.set()
        try:
            controller.stop()
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
        ctrl_store.close()
        for s in shard_stores:
            s.close()
        ctrl_srv.stop()
        for srv in shard_srvs:
            srv.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--templates", type=int, default=24)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--stagger", type=float, default=0.0,
                    help="seconds between template creates (0 = burst)")
    ap.add_argument("--shards", type=int, default=1,
                    help="number of in-process shard API servers")
    ap.add_argument("--shard-sync-workers", type=int, default=0,
                    help="shard fan-out bound: 0 = auto (parallel), "
                         "1 = sequential reference baseline")
    ap.add_argument("--no-write-skip", action="store_true",
                    help="disable the content-hash write-skip cache "
                         "(pre-change baseline mode)")
    ap.add_argument("--shard-latency", type=float, default=0.0,
                    help="simulated per-request RTT to shard API servers, "
                         "seconds (models remote shard clusters)")
    args = ap.parse_args(argv)
    result = run_bench(args.templates, args.workers, args.timeout,
                       args.stagger, args.shards, args.shard_sync_workers,
                       write_skip=not args.no_write_skip,
                       shard_latency_s=args.shard_latency)
    print(json.dumps(result), flush=True)
    return 0 if "value" in result else 1


if __name__ == "__main__":
    sys.exit(main())
