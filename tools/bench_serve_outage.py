"""Hermetic serve-outage bench: engine killed mid-decode → detector
confirms → drain-and-requeue → token-identical completion (CPU-only).

The serving twin of tools/bench_failover.py. A stub-model engine (next
token = (t + 1) mod v — deterministic, compile-light, seconds on CPU)
serves a shared-prefix queue under the ServeEngineSupervisor
(ha/serve_failover.py): the engine renews its ``hb-serve-<template>``
lease at wave boundaries, a chaos thread kills it mid-decode once enough
tokens have committed (odd trials wedge the lease via ``freeze_engine``
— detector-confirm-without-crash; even trials hard-kill the engine —
confirmation by silence), the real FailureDetector confirms, and the
planner requeues every unfinished request with its committed tokens
folded into the prompt.

Measured per trial:

  time_to_recover = confirmation → the replacement engine's lease live
                    (the serving plane is back in business)
  detection       = first missed renewal → confirmation
  requests_lost   = results still None after recovery (MUST be 0)
  exact           = every recovered stream token-identical to an
                    undisturbed run of the same queue

plus one overload leg (no chaos): a burst past ``max_queue_depth`` on a
bounded-queue engine with per-request deadlines — shed rate and
deadline-miss rate prove load shedding stays honest under pressure.

Prints ONE JSON line: {"metric": "serve_outage_time_to_recover_s", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from types import SimpleNamespace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cyclic_model(v: int):
    """Deterministic stub: next = (token + 1) % v. The engine's
    scheduling/failover machinery is model-agnostic, so the stub proves
    requeue exactness without a single weight or compile-heavy program
    (the llama-backed exactness tiers live in tests/)."""
    import jax
    import jax.numpy as jnp

    cfg = SimpleNamespace(
        n_layers=1, n_kv_heads=1, head_dim=8, dtype=jnp.float32,
        max_seq_len=512, vocab_size=v,
    )

    def fwd(params, cfg_, tokens, cache):
        logits = jax.nn.one_hot((tokens + 1) % v, v) * 10.0
        new = {k: x for k, x in cache.items() if k != "n_valid"}
        nv = cache.get("n_valid")
        adv = tokens.shape[1] if nv is None else nv
        new["length"] = cache["length"] + adv
        return logits.astype(jnp.float32), new

    return cfg, fwd


def _queue(v: int, n: int, shared: int, max_new: int):
    """Shared-prefix queue (the prefix cache dedupes the preamble on the
    replacement engine exactly as on the one that died)."""
    from nexus_tpu.runtime.serving import ServeRequest

    common = [(7 * i + 3) % v for i in range(shared)]
    reqs = []
    for i in range(n):
        tail = [(3 * i + j) % v for j in range(4)]
        reqs.append(ServeRequest(
            prompt=common + tail, max_new_tokens=max_new,
        ))
    return reqs


def _expected(req, v: int):
    out = [int(t) for t in req.prompt]
    cur = out[-1]
    for _ in range(req.max_new_tokens):
        cur = (cur + 1) % v
        out.append(cur)
    return out


def _one_trial(trial: int, v: int, reqs, ttl: float, pace: float,
               kill_after: int, timeout: float):
    from nexus_tpu.api.types import ConfigMap
    from nexus_tpu.cluster.store import ClusterStore, NotFoundError
    from nexus_tpu.ha.lease import heartbeat_name
    from nexus_tpu.ha.serve_failover import (
        ServeEngineSupervisor,
        freeze_engine,
        serve_heartbeat_template,
    )
    from nexus_tpu.runtime.serving import ServingEngine

    cfg, fwd = _cyclic_model(v)

    def make_engine():
        return ServingEngine(
            fwd, {}, cfg, batch_size=2, max_len=256, chunk=4,
            kv_block_size=8,
        )

    template = f"outage-{trial}"
    store = ClusterStore(f"serve-shard-{trial}")
    sup = ServeEngineSupervisor(
        make_engine, store, "nexus", template,
        ttl_seconds=ttl, pace_s=pace,
    )
    kill_t = [0.0]
    mode = "freeze" if trial % 2 else "kill"

    def chaos():
        name = heartbeat_name(serve_heartbeat_template(template))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                cm = store.get(ConfigMap.KIND, "nexus", name)
            except NotFoundError:
                time.sleep(0.005)
                continue
            step = int((cm.data or {}).get("step", "0") or 0)
            if step >= kill_after:
                kill_t[0] = time.monotonic()
                if mode == "freeze":
                    freeze_engine(store, "nexus", template)
                else:
                    sup.kill_current(hard=True)
                return
            time.sleep(0.005)

    chaos_thread = threading.Thread(target=chaos, daemon=True)
    chaos_thread.start()
    results, report = sup.run(reqs, timeout_s=timeout)
    done_t = time.monotonic()
    exact = all(
        r is not None and r.tokens == _expected(req, v)
        for req, r in zip(reqs, results)
    )
    return {
        "mode": mode,
        "restarts": report["restarts"],
        "requests_lost": report["requests_lost"],
        "exact": exact,
        "detection_s": (
            report["detections_s"][0] if report["detections_s"] else None
        ),
        "time_to_recover_s": (
            report["recover_s"][0] if report["recover_s"] else None
        ),
        "outage_to_complete_s": (
            done_t - kill_t[0] if kill_t[0] else None
        ),
        "failed_over": sum(
            1 for r in results
            if r is not None and r.status == "failed_over"
        ),
        "kv_leaked_blocks": sum(
            g.get("kv_allocated_blocks_final", 0)
            + g.get("kv_reserved_blocks_final", 0)
            for g in report["generations"]
        ),
    }


def _overload_leg(v: int):
    """Bounded-queue shedding under a burst — no chaos, pure policing:
    12 requests into a 2-row engine bounded at depth 4, three of them
    carrying a sub-millisecond deadline. Sheds and misses must be
    explicit statuses, never queue growth."""
    from nexus_tpu.runtime.serving import ServeRequest, ServingEngine

    cfg, fwd = _cyclic_model(v)
    engine = ServingEngine(
        fwd, {}, cfg, batch_size=2, max_len=256, chunk=4,
        kv_block_size=8, max_queue_depth=4,
    )
    reqs = []
    for i in range(12):
        reqs.append(ServeRequest(
            prompt=[(i + j) % v for j in range(6)], max_new_tokens=24,
            priority=i % 3,
            deadline_s=1e-6 if i in (9, 10, 11) else 0.0,
        ))
    results, m = engine.serve(reqs)
    assert all(r is not None for r in results)
    return {
        "shed_rate": m["shed_rate"],
        "deadline_miss_rate": m["deadline_miss_rate"],
        "queue_depth_peak": m["queue_depth_peak"],
        "ok_requests": m["ok_requests"],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--ttl", type=float, default=0.15)
    ap.add_argument("--pace", type=float, default=0.008)
    args = ap.parse_args()

    from nexus_tpu.utils.telemetry import percentile_nearest_rank

    def _p50(xs):
        """Nearest-rank p50 rounded for the artifact, None for an empty
        population — NaN must never reach the JSON line (json.dumps
        would emit the non-standard `NaN` token and break every strict
        consumer of the per-round artifact)."""
        return round(percentile_nearest_rank(xs, 0.50), 4) if xs else None

    v = 13
    # enough decode runway (with the per-wave pace) that a freeze trial's
    # engine is still serving when the detector confirms — a queue that
    # drains inside the detection window would recover trivially
    reqs = _queue(v, n=8, shared=16, max_new=90)
    trials = []
    for i in range(args.trials):
        try:
            trials.append(_one_trial(
                i, v, reqs, ttl=args.ttl, pace=args.pace,
                kill_after=20, timeout=args.timeout,
            ))
        except Exception as e:  # noqa: BLE001 — report, don't crash
            print(json.dumps({
                "error": f"trial {i}: {type(e).__name__}: {e}"
            }))
            return 1
    recover = [t["time_to_recover_s"] for t in trials
               if t["time_to_recover_s"] is not None]
    detect = [t["detection_s"] for t in trials
              if t["detection_s"] is not None]
    lost = sum(t["requests_lost"] for t in trials)
    leaked = sum(t["kv_leaked_blocks"] for t in trials)
    overload = _overload_leg(v)
    rec = {
        "metric": "serve_outage_time_to_recover_s",
        "value": _p50(recover),
        "unit": "seconds",
        "n_trials": len(trials),
        "requests_lost": lost,
        "kv_leaked_blocks": leaked,
        "exact": all(t["exact"] for t in trials),
        "detection_p50_s": _p50(detect),
        "outage_to_complete_p50_s": _p50(
            [t["outage_to_complete_s"] for t in trials
             if t["outage_to_complete_s"] is not None],
        ),
        "restarts_total": sum(t["restarts"] for t in trials),
        "failed_over_total": sum(t["failed_over"] for t in trials),
        "shed_rate": overload["shed_rate"],
        "deadline_miss_rate": overload["deadline_miss_rate"],
        "overload_queue_depth_peak": overload["queue_depth_peak"],
    }
    print(json.dumps(rec))
    # honest exit: a lost request, a leaked block, an inexact recovery,
    # or a round where the chaos never landed (nothing was proven) is a
    # FAILED bench even when the timing numbers look fine
    ok = (lost == 0 and leaked == 0 and rec["exact"]
          and rec["restarts_total"] >= 1)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
