"""Capture an XLA profile of N train steps through the product runtime path.

Usage (on the TPU host):
    python tools/profile_train.py [out_dir]
    python tools/trace_summary.py [out_dir]

Env knobs: P_ATTN (xla|flash), P_REMAT (none|dots|dots_attn|full),
P_BATCH, P_SEQ, P_PRESET, P_HEADS ("hq,hkv" head-layout override) —
mirror the bench sweep's candidate axes (bench.py).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nexus_tpu.api.runtime_spec import (  # noqa: E402
    JaxXlaRuntime, ModelRef, ParallelismSpec, ProfileSpec, TrainSpec,
)
from nexus_tpu.runtime.entrypoints import run_template_runtime  # noqa: E402


def main() -> int:
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/nexus_prof"
    attn = os.environ.get("P_ATTN", "xla")
    remat = os.environ.get("P_REMAT", "dots")
    overrides = {"attn_impl": attn}
    heads = os.environ.get("P_HEADS")
    if heads:
        hq, hkv = (int(x) for x in heads.split(","))
        overrides["n_heads"], overrides["n_kv_heads"] = hq, hkv
    if remat == "none":
        overrides["remat"] = False
    else:
        overrides["remat"] = True
        overrides["remat_policy"] = remat

    runtime = JaxXlaRuntime(
        mode="train",
        model=ModelRef(
            family="llama",
            preset=os.environ.get("P_PRESET", "400m"),
            overrides=overrides,
        ),
        parallelism=ParallelismSpec(),
        train=TrainSpec(
            batch_size=int(os.environ.get("P_BATCH", "8")),
            seq_len=int(os.environ.get("P_SEQ", "2048")),
            steps=7,
            learning_rate=3e-4,
        ),
        profile=ProfileSpec(
            enabled=True, directory=out_dir, start_step=2, num_steps=3
        ),
    )
    m = run_template_runtime(runtime)
    print({k: m.get(k) for k in (
        "mfu", "tokens_per_sec_per_chip", "steps_per_sec", "final_loss"
    )})
    print(f"trace in {out_dir}; summarize with tools/trace_summary.py")
    return 0


if __name__ == "__main__":
    sys.exit(main())
