"""On-chip decode-step cost probe for the serving engine.

Times the serving chunk programs directly — width-1 (pure decode) and
the prefill-width program — at several row counts, plus the static
batch-1 decode step as the reference. This isolates WHERE serving
throughput goes: per-step model cost vs feed width vs row count vs
dispatch/host overhead (the per-chunk host fetch pays one tunnel RTT).

    python tools/probe_serve_step.py            # on the attached TPU
    NEXUS_PROBE_ROWS=1,8,16 NEXUS_PROBE_CHUNK=32 ...

Prints one JSON line: ms/step per (rows, width) plus derived
aggregate tokens/sec ceilings (rows / step_time).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from nexus_tpu.utils.hw import device_kind, honor_env_platforms

    honor_env_platforms()
    from nexus_tpu.utils.hw import enable_persistent_compilation_cache

    # tunnel-compile cache shared with bench.py (helper no-ops unless the
    # resolved backend is a real TPU or NEXUS_XLA_CACHE_DIR opts in)
    enable_persistent_compilation_cache(repo_default=True)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nexus_tpu.models import llama
    from nexus_tpu.runtime.serving import ServingEngine

    print(f"[probe] backend: {device_kind()}", file=sys.stderr, flush=True)
    rows_list = [
        int(r) for r in
        (os.environ.get("NEXUS_PROBE_ROWS") or "1,8,16").split(",")
    ]
    chunk = int(os.environ.get("NEXUS_PROBE_CHUNK") or 32)
    max_len = int(os.environ.get("NEXUS_PROBE_MAXLEN") or 1024)
    preset = os.environ.get("NEXUS_PROBE_PRESET") or "400m"
    cfg = llama.config(preset)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    out = {"preset": preset, "chunk": chunk, "max_len": max_len}

    for rows in rows_list:
        for width in (1, 16):
            # NEXUS_PROBE_KV_BLOCK: block size of the paged cache the
            # engine now serves by default (0 probes the legacy dense
            # layout) — the probe must time the LAYOUT the engine runs
            kvb = int(os.environ.get("NEXUS_PROBE_KV_BLOCK") or 32)
            eng = ServingEngine(
                llama.forward_decode, params, cfg, batch_size=rows,
                max_len=max_len, chunk=chunk, prefill_chunk=width,
                kv_block_size=kvb,
            )
            fn = (eng._decode_chunk if width > 1
                  else eng._decode_chunk_narrow)
            from nexus_tpu.models.decoding import (
                init_kv_cache,
                init_paged_kv_cache,
            )

            def fresh():
                if kvb > 0:
                    m = -(-max_len // kvb)
                    nb = rows * m  # capacity-equivalent pool (+1 scratch)
                    c = init_paged_kv_cache(
                        cfg.n_layers, cfg.n_kv_heads, cfg.head_dim,
                        cfg.dtype, rows, nb + 1, kvb, m,
                    )
                    # fully-mapped tables: the steady-state gather cost
                    c["block_table"] = jnp.arange(
                        rows * m, dtype=jnp.int32
                    ).reshape(rows, m)
                else:
                    c = init_kv_cache(
                        cfg.n_layers, cfg.n_kv_heads, cfg.head_dim,
                        cfg.dtype, rows, max_len,
                    )
                c["length"] = jnp.full((rows,), 128, jnp.int32)
                return c

            zi = lambda: jnp.zeros((rows,), jnp.int32)  # noqa: E731
            zf = lambda: jnp.zeros((rows,), jnp.float32)  # noqa: E731
            buf = jnp.zeros((rows, max_len), jnp.int32)
            done = jnp.zeros((rows,), jnp.bool_)
            # compile + warm (fresh donated buffers per call)
            res = fn(params, fresh(), zi(), zi(), done, buf, zi(),
                     zf(), zi())
            np.asarray(res[3])
            times = []
            for _ in range(3):
                cache = fresh()
                t0 = time.monotonic()
                res = fn(params, cache, zi(), zi(), done, buf, zi(),
                         zf(), zi())
                np.asarray(res[3])  # host fetch closes the window
                times.append(time.monotonic() - t0)
            best = min(times)
            ms_per_step = best / chunk * 1e3
            key = f"rows{rows}_w{width}"
            out[f"{key}_ms_per_step"] = round(ms_per_step, 3)
            out[f"{key}_ceiling_tok_s"] = round(rows / (best / chunk), 1)
            print(
                f"[probe] rows={rows} width={width}: "
                f"{ms_per_step:.2f} ms/step "
                f"(ceiling {rows / (best / chunk):.0f} tok/s)",
                file=sys.stderr, flush=True,
            )
    if os.environ.get("NEXUS_PROBE_PREFIX", "") not in ("", "0", "false"):
        # end-to-end shared-prefix serve leg (round 6): 16 requests
        # sharing a 192-token system prompt with distinct tails, prefix
        # cache on vs off (off == the PR 2 paged engine) — reports the
        # hit tokens, the prefill step-slots the cache saved, and the
        # per-request KV reservation reduction, on whatever backend the
        # probe is attached to
        from nexus_tpu.runtime.serving import ServeRequest

        rng = np.random.RandomState(0)
        common = rng.randint(0, cfg.vocab_size, size=192).tolist()
        reqs = [
            ServeRequest(
                prompt=common
                + rng.randint(0, cfg.vocab_size,
                              size=int(rng.randint(8, 33))).tolist(),
                max_new_tokens=int(rng.randint(32, 65)),
            )
            for _ in range(16)
        ]
        legs = {}
        for cache_on in (True, False):
            eng = ServingEngine(
                llama.forward_decode, params, cfg, batch_size=8,
                max_len=max_len, chunk=chunk, prefill_chunk=16,
                kv_block_size=int(
                    os.environ.get("NEXUS_PROBE_KV_BLOCK") or 32
                ) or 32,
                prefix_cache=cache_on,
            )
            _, m = eng.serve(reqs)
            legs[cache_on] = m
            tag = "prefix_on" if cache_on else "prefix_off"
            out[f"{tag}_prefill_steps"] = m["prefill_steps"]
            out[f"{tag}_kv_bytes_per_request"] = m["kv_bytes_per_request"]
            out[f"{tag}_tokens_per_sec"] = m["tokens_per_sec"]
        out["prefix_hit_tokens"] = legs[True].get("prefix_hit_tokens")
        out["prefix_prefill_steps_saved"] = legs[True].get(
            "prefix_prefill_steps_saved"
        )
        out["prefix_prefill_steps_reduction"] = round(
            legs[False]["prefill_steps"]
            / max(1, legs[True]["prefill_steps"]), 3,
        )
        print(
            "[probe] shared-prefix: steps "
            f"{legs[False]['prefill_steps']}→{legs[True]['prefill_steps']}"
            f" hit_tokens={out['prefix_hit_tokens']}",
            file=sys.stderr, flush=True,
        )
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
